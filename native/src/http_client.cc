#include "tpuclient/http_client.h"

#include <zlib.h>

#include <algorithm>

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <climits>
#include <cstring>

#include "tpuclient/base64.h"

#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

namespace tpuclient {

// ---------------------------------------------------------------------------
// HttpConnection: one keep-alive HTTP/1.1 connection over a POSIX socket
// ---------------------------------------------------------------------------

class HttpConnection {
 public:
  HttpConnection(const std::string& host, int port, const TlsOptions& tls)
      : host_(host), port_(port), fd_(-1), tls_opts_(tls) {}
  ~HttpConnection() { Close(); }

  void Close() {
    if (tls_) {
      tls_->Close();
      tls_.reset();
    }
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
    rbuf_.clear();
  }

  Error EnsureConnected() {
    if (fd_ >= 0) return Error::Success();
    struct addrinfo hints;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_str = std::to_string(port_);
    int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
    if (rc != 0) {
      return Error("failed to resolve " + host_ + ": " + gai_strerror(rc),
                   400);
    }
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      fd_ = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      if (::connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) break;
      ::close(fd_);
      fd_ = -1;
    }
    freeaddrinfo(res);
    if (fd_ < 0) {
      return Error("failed to connect to " + host_ + ":" + port_str, 400);
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (tls_opts_.use_ssl) {
      tls_ = std::make_unique<TlsSession>();
      Error err = tls_->Handshake(fd_, host_, tls_opts_);
      if (!err.IsOk()) {
        Close();
        return err;
      }
      // Non-blocking after the (blocking) handshake: a partial TLS record
      // must surface as kWantRead back to Fill's deadline loop, not as an
      // SSL_read that camps past the request timeout.
      int fl = ::fcntl(fd_, F_GETFL, 0);
      ::fcntl(fd_, F_SETFL, fl | O_NONBLOCK);
    }
    return Error::Success();
  }

  // Sends headers + scatter-gather body segments with writev.
  Error SendRequest(const std::string& head,
                    const std::vector<std::pair<const uint8_t*, size_t>>& segs) {
    Error err = EnsureConnected();
    if (!err.IsOk()) return err;
    std::vector<struct iovec> iov;
    iov.reserve(segs.size() + 1);
    iov.push_back({const_cast<char*>(head.data()), head.size()});
    for (const auto& s : segs) {
      if (s.second > 0)
        iov.push_back({const_cast<uint8_t*>(s.first), s.second});
    }
    size_t idx = 0;
    if (tls_) {
      for (const auto& v : iov) {
        size_t off = 0;
        while (off < v.iov_len) {
          Error werr;
          ssize_t n = tls_->Write(static_cast<char*>(v.iov_base) + off,
                                  v.iov_len - off, &werr);
          if (n == TlsSession::kWantWrite || n == TlsSession::kWantRead) {
            struct pollfd pfd{
                fd_, short(n == TlsSession::kWantWrite ? POLLOUT : POLLIN),
                0};
            ::poll(&pfd, 1, 1000);
            continue;
          }
          if (n <= 0) {
            Close();
            return werr.IsOk() ? Error("TLS send closed", 400) : werr;
          }
          off += static_cast<size_t>(n);
        }
      }
      return Error::Success();
    }
    while (idx < iov.size()) {
      ssize_t n = ::writev(fd_, iov.data() + idx,
                           static_cast<int>(
                               std::min<size_t>(iov.size() - idx, IOV_MAX)));
      if (n < 0) {
        if (errno == EINTR) continue;
        Close();
        return Error(std::string("send failed: ") + strerror(errno), 400);
      }
      size_t sent = static_cast<size_t>(n);
      while (idx < iov.size() && sent >= iov[idx].iov_len) {
        sent -= iov[idx].iov_len;
        ++idx;
      }
      if (idx < iov.size() && sent > 0) {
        iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + sent;
        iov[idx].iov_len -= sent;
      }
    }
    return Error::Success();
  }

  // Reads one full HTTP response. timeout_us==0 means no timeout.
  // One send+read round trip with a single whole-request retry on a stale
  // keep-alive socket. A pooled connection the server closed after its idle
  // timeout fails either at send (RST) or — more commonly — with a clean
  // EOF at read even though writev() was accepted into the half-closed
  // socket's buffer; both are safe to retry on a fresh connection because
  // no response bytes ever arrived. Timeouts (499) and partial responses
  // are NOT retried.
  Error RoundTrip(const std::string& head,
                  const std::vector<std::pair<const uint8_t*, size_t>>& segs,
                  uint64_t timeout_us, int* status, Headers* headers,
                  std::string* body, RequestTimers* timers = nullptr) {
    bool reused = fd_ >= 0;
    if (timers) timers->Capture(RequestTimers::Kind::SEND_START);
    Error err = SendRequest(head, segs);
    bool need_retry = false;
    if (err.IsOk()) {
      if (timers) timers->Capture(RequestTimers::Kind::SEND_END);
      got_bytes_ = !rbuf_.empty();
      first_byte_ns_ = 0;
      err = ReadResponse(status, headers, body, timeout_us);
      // RECV_START = first response byte (matches the reference's curl
      // semantics); the wait for the server to answer lands in the derived
      // "Network+Server Send/Recv" metric instead of client receive time.
      if (timers) {
        timers->recv_start_ns =
            first_byte_ns_ ? first_byte_ns_ : RequestTimers::Now();
        timers->Capture(RequestTimers::Kind::RECV_END);
      }
      if (err.IsOk()) return err;
      need_retry = reused && !got_bytes_ && err.StatusCode() != 499;
    } else {
      need_retry = reused;
    }
    if (!need_retry) return err;
    Close();
    if (timers) timers->Capture(RequestTimers::Kind::SEND_START);
    err = SendRequest(head, segs);
    if (!err.IsOk()) return err;
    if (timers) timers->Capture(RequestTimers::Kind::SEND_END);
    got_bytes_ = false;  // fresh connection, fresh first-byte tracking
    first_byte_ns_ = 0;
    err = ReadResponse(status, headers, body, timeout_us);
    if (timers) {
      timers->recv_start_ns =
          first_byte_ns_ ? first_byte_ns_ : RequestTimers::Now();
      timers->Capture(RequestTimers::Kind::RECV_END);
    }
    return err;
  }

  Error ReadResponse(int* status, Headers* headers, std::string* body,
                     uint64_t timeout_us) {
    uint64_t deadline_ns =
        timeout_us ? RequestTimers::Now() + timeout_us * 1000 : 0;
    std::string head;
    // --- status line + headers ---
    size_t header_end;
    while (true) {
      header_end = rbuf_.find("\r\n\r\n");
      if (header_end != std::string::npos) break;
      Error err = Fill(deadline_ns);
      if (!err.IsOk()) return err;
    }
    head = rbuf_.substr(0, header_end);
    rbuf_.erase(0, header_end + 4);

    size_t line_end = head.find("\r\n");
    std::string status_line =
        line_end == std::string::npos ? head : head.substr(0, line_end);
    if (status_line.size() < 12 || status_line.compare(0, 5, "HTTP/") != 0) {
      Close();
      return Error(
          "malformed HTTP status line: " + SanitizeForLog(status_line), 400);
    }
    *status = atoi(status_line.c_str() + 9);

    headers->clear();
    size_t pos = line_end == std::string::npos ? head.size() : line_end + 2;
    bool chunked = false;
    ssize_t content_length = -1;
    bool close_conn = false;
    while (pos < head.size()) {
      size_t eol = head.find("\r\n", pos);
      if (eol == std::string::npos) eol = head.size();
      std::string line = head.substr(pos, eol - pos);
      pos = eol + 2;
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string key = line.substr(0, colon);
      size_t vstart = colon + 1;
      while (vstart < line.size() && line[vstart] == ' ') ++vstart;
      std::string value = line.substr(vstart);
      for (auto& c : key) c = static_cast<char>(tolower(c));
      (*headers)[key] = value;
      if (key == "content-length") content_length = atoll(value.c_str());
      if (key == "transfer-encoding" &&
          value.find("chunked") != std::string::npos)
        chunked = true;
      if (key == "connection" && value.find("close") != std::string::npos)
        close_conn = true;
    }

    body->clear();
    if (chunked) {
      Error err = ReadChunked(body, deadline_ns);
      if (!err.IsOk()) return err;
    } else if (content_length >= 0) {
      while (rbuf_.size() < static_cast<size_t>(content_length)) {
        Error err = Fill(deadline_ns);
        if (!err.IsOk()) return err;
      }
      body->assign(rbuf_, 0, content_length);
      rbuf_.erase(0, content_length);
    } else {
      // read until close
      while (true) {
        Error err = Fill(deadline_ns);
        if (!err.IsOk()) break;
      }
      body->swap(rbuf_);
      rbuf_.clear();
      Close();
    }
    if (close_conn) Close();
    return Error::Success();
  }

 private:
  // Waits (≤ deadline) for the fd to become readable/writable. Returns a
  // 499 on deadline expiry; EINTR and spurious wakeups return Success (the
  // caller's read loop re-enters).
  Error PollFd(short events, uint64_t deadline_ns) {
    int timeout_ms = -1;
    if (deadline_ns) {
      uint64_t now = RequestTimers::Now();
      if (now >= deadline_ns) {
        Close();
        return Error("Deadline Exceeded", 499);
      }
      timeout_ms = static_cast<int>((deadline_ns - now) / 1000000) + 1;
    }
    struct pollfd pfd{fd_, events, 0};
    int prc = ::poll(&pfd, 1, timeout_ms);
    if (prc == 0) {
      Close();
      return Error("Deadline Exceeded", 499);
    }
    if (prc < 0 && errno != EINTR) {
      Close();
      return Error(std::string("poll failed: ") + strerror(errno), 400);
    }
    return Error::Success();
  }

  Error Fill(uint64_t deadline_ns) {
    if (fd_ < 0) return Error("connection closed", 400);
    char buf[65536];
    ssize_t n;
    if (tls_) {
      // Bytes already decrypted inside the TLS layer are readable now even
      // though poll() on the fd would block; otherwise the non-blocking
      // SSL_read surfaces kWantRead/kWantWrite and the deadline-aware poll
      // decides how long to wait for the rest of the record.
      while (true) {
        Error rerr;
        n = tls_->Read(buf, sizeof(buf), &rerr);
        if (n == TlsSession::kWantRead || n == TlsSession::kWantWrite) {
          Error perr = PollFd(
              n == TlsSession::kWantRead ? POLLIN : POLLOUT, deadline_ns);
          if (!perr.IsOk()) return perr;
          continue;
        }
        if (n == 0) {
          Close();
          return Error("connection closed by server", 400);
        }
        if (n < 0) {
          Close();
          return rerr.IsOk() ? Error("TLS read failed", 400) : rerr;
        }
        break;
      }
    } else {
      if (deadline_ns) {
        Error perr = PollFd(POLLIN, deadline_ns);
        if (!perr.IsOk()) return perr;
      }
      n = ::recv(fd_, buf, sizeof(buf), 0);
      if (n == 0) {
        Close();
        return Error("connection closed by server", 400);
      }
      if (n < 0) {
        if (errno == EINTR) return Error::Success();
        Close();
        return Error(std::string("recv failed: ") + strerror(errno), 400);
      }
    }
    rbuf_.append(buf, n);
    if (!got_bytes_) first_byte_ns_ = RequestTimers::Now();
    got_bytes_ = true;
    return Error::Success();
  }

  Error ReadChunked(std::string* body, uint64_t deadline_ns) {
    while (true) {
      size_t eol;
      while ((eol = rbuf_.find("\r\n")) == std::string::npos) {
        Error err = Fill(deadline_ns);
        if (!err.IsOk()) return err;
      }
      size_t chunk_size = strtoul(rbuf_.c_str(), nullptr, 16);
      rbuf_.erase(0, eol + 2);
      if (chunk_size == 0) {
        while (rbuf_.find("\r\n") == std::string::npos) {
          Error err = Fill(deadline_ns);
          if (!err.IsOk()) return err;
        }
        rbuf_.erase(0, rbuf_.find("\r\n") + 2);
        return Error::Success();
      }
      while (rbuf_.size() < chunk_size + 2) {
        Error err = Fill(deadline_ns);
        if (!err.IsOk()) return err;
      }
      body->append(rbuf_, 0, chunk_size);
      rbuf_.erase(0, chunk_size + 2);  // chunk + CRLF
    }
  }

  std::string host_;
  int port_;
  int fd_;
  TlsOptions tls_opts_;
  std::unique_ptr<TlsSession> tls_;
  std::string rbuf_;
  // whether any response byte arrived for the in-flight request (guards the
  // RoundTrip stale-connection retry against replaying a half-answered call)
  bool got_bytes_ = false;
  uint64_t first_byte_ns_ = 0;
};

// ---------------------------------------------------------------------------
// InferResultHttp
// ---------------------------------------------------------------------------

// Flattened JSON data array → packed little-endian bytes (the inverse of the
// server's JSON tensor encoding; BYTES elements become 4-byte-LE
// length-prefixed).
static Error MaterializeJsonData(const Json& data, const std::string& datatype,
                                 std::string* out) {
  size_t elem = DtypeByteSize(datatype);
  out->reserve(data.Size() * (elem ? elem : 8));
  for (size_t i = 0; i < data.Size(); ++i) {
    const JsonPtr& v = data.At(i);
    if (datatype == "BYTES") {
      const std::string& s = v->AsString();
      uint32_t len = static_cast<uint32_t>(s.size());
      out->append(reinterpret_cast<const char*>(&len), 4);
      out->append(s);
    } else if (datatype == "FP32") {
      float f = static_cast<float>(v->AsDouble());
      out->append(reinterpret_cast<const char*>(&f), 4);
    } else if (datatype == "FP64") {
      double d = v->AsDouble();
      out->append(reinterpret_cast<const char*>(&d), 8);
    } else if (datatype == "BOOL") {
      char b = v->AsBool() ? 1 : 0;
      out->append(&b, 1);
    } else if (elem > 0) {
      int64_t n = v->AsInt();
      uint64_t u = v->AsUint();
      const char* src = (datatype[0] == 'U')
                            ? reinterpret_cast<const char*>(&u)
                            : reinterpret_cast<const char*>(&n);
      out->append(src, elem);  // little-endian truncation
    } else {
      return Error("cannot materialize JSON data for datatype '" + datatype +
                       "'",
                   400);
    }
  }
  return Error::Success();
}

Error InferResultHttp::Create(InferResult** result, std::string&& response_body,
                              size_t header_length, int http_status) {
  auto* res = new InferResultHttp();
  res->body_ = std::move(response_body);
  if (header_length > res->body_.size()) {
    delete res;
    return Error("Inference-Header-Content-Length " +
                     std::to_string(header_length) + " exceeds body size",
                 400);
  }
  size_t head_len = header_length ? header_length : res->body_.size();
  Error err = Json::Parse(res->body_.data(), head_len, &res->head_);
  if (!err.IsOk()) {
    delete res;
    return err;
  }
  if (http_status != 200) {
    JsonPtr msg = res->head_->IsObject() ? res->head_->Get("error") : nullptr;
    res->status_ = Error(msg && msg->IsString() ? msg->AsString()
                                                : "inference failed",
                         http_status);
    *result = res;
    return Error::Success();
  }
  res->status_ = Error::Success();

  // Walk outputs; binary ones consume body bytes after the head, in order
  // (reference binary-offset output mapping, http_client.cc:752-835).
  const uint8_t* cursor =
      reinterpret_cast<const uint8_t*>(res->body_.data()) + head_len;
  size_t remaining = res->body_.size() - head_len;
  JsonPtr outputs = res->head_->Get("outputs");
  if (outputs && outputs->IsArray()) {
    for (size_t i = 0; i < outputs->Size(); ++i) {
      JsonPtr out = outputs->At(i);
      if (!out->IsObject()) continue;
      JsonPtr name = out->Get("name");
      if (!name || !name->IsString()) continue;
      OutputRef ref;
      ref.meta = out;
      JsonPtr params = out->Get("parameters");
      bool is_binary = false;
      if (params && params->IsObject()) {
        JsonPtr bds = params->Get("binary_data_size");
        if (bds && bds->IsNumber()) {
          is_binary = true;
          size_t sz = static_cast<size_t>(bds->AsUint());
          if (sz > remaining) {
            delete res;
            return Error("binary output '" + name->AsString() +
                             "' overruns response body",
                         400);
          }
          ref.data = cursor;
          ref.byte_size = sz;
          cursor += sz;
          remaining -= sz;
        }
      }
      if (!is_binary) {
        // JSON data array: materialize packed little-endian bytes so
        // RawData/StringData work uniformly regardless of response form.
        JsonPtr data = out->Get("data");
        JsonPtr dt = out->Get("datatype");
        if (data && data->IsArray() && dt && dt->IsString()) {
          ref.json_backing = std::make_shared<std::string>();
          Error merr =
              MaterializeJsonData(*data, dt->AsString(), ref.json_backing.get());
          if (!merr.IsOk()) {
            delete res;
            return merr;
          }
          ref.data = reinterpret_cast<const uint8_t*>(ref.json_backing->data());
          ref.byte_size = ref.json_backing->size();
        }
      }
      res->outputs_[name->AsString()] = std::move(ref);
    }
  }
  *result = res;
  return Error::Success();
}

Error InferResultHttp::ModelName(std::string* name) const {
  JsonPtr v = head_->Get("model_name");
  if (!v || !v->IsString()) return Error("no model_name in response");
  *name = v->AsString();
  return Error::Success();
}
Error InferResultHttp::ModelVersion(std::string* version) const {
  JsonPtr v = head_->Get("model_version");
  if (!v || !v->IsString()) return Error("no model_version in response");
  *version = v->AsString();
  return Error::Success();
}
Error InferResultHttp::Id(std::string* id) const {
  JsonPtr v = head_->Get("id");
  *id = (v && v->IsString()) ? v->AsString() : "";
  return Error::Success();
}

Error InferResultHttp::Shape(const std::string& output_name,
                             std::vector<int64_t>* shape) const {
  auto it = outputs_.find(output_name);
  if (it == outputs_.end())
    return Error("output '" + output_name + "' not found");
  JsonPtr s = it->second.meta->Get("shape");
  if (!s || !s->IsArray()) return Error("output has no shape");
  shape->clear();
  for (size_t i = 0; i < s->Size(); ++i) shape->push_back(s->At(i)->AsInt());
  return Error::Success();
}

Error InferResultHttp::Datatype(const std::string& output_name,
                                std::string* datatype) const {
  auto it = outputs_.find(output_name);
  if (it == outputs_.end())
    return Error("output '" + output_name + "' not found");
  JsonPtr d = it->second.meta->Get("datatype");
  if (!d || !d->IsString()) return Error("output has no datatype");
  *datatype = d->AsString();
  return Error::Success();
}

Error InferResultHttp::RawData(const std::string& output_name,
                               const uint8_t** buf, size_t* byte_size) const {
  auto it = outputs_.find(output_name);
  if (it == outputs_.end())
    return Error("output '" + output_name + "' not found");
  if (it->second.data == nullptr)
    return Error("output '" + output_name +
                 "' returned as JSON data; request binary_data");
  *buf = it->second.data;
  *byte_size = it->second.byte_size;
  return Error::Success();
}

Error InferResultHttp::RequestStatus() const { return status_; }

std::string InferResultHttp::DebugString() const {
  return head_ ? head_->Serialize() : "<empty>";
}


// ---------------------------------------------------------------------------
// Compression (reference CompressData / CURLOPT_ACCEPT_ENCODING,
// http_client.cc:122-198, 1547-1557)
// ---------------------------------------------------------------------------

Error InferenceServerHttpClient::CompressRequest(PreparedRequest* prep,
                                                 CompressionType type) {
  if (type == CompressionType::NONE) return Error::Success();
  std::string whole;
  whole.reserve(prep->total_body);
  whole.append(prep->json_head);
  for (const auto& seg : prep->tail)
    whole.append(reinterpret_cast<const char*>(seg.first), seg.second);
  Error err =
      zutil::Deflate(whole, type == CompressionType::GZIP, &prep->compressed);
  if (!err.IsOk()) {
    return Error("request compression failed: " + err.Message(), 400);
  }
  prep->content_encoding =
      type == CompressionType::GZIP ? "gzip" : "deflate";
  // Inference-Header-Content-Length still names the *uncompressed* JSON
  // head size; the server decompresses first, then splits.
  prep->total_body = prep->compressed.size();
  prep->tail.clear();
  return Error::Success();
}

// ---------------------------------------------------------------------------
// InferenceServerHttpClient
// ---------------------------------------------------------------------------

Error InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, bool verbose,
    const HttpSslOptions& ssl_options) {
  std::string host;
  int port;
  std::string scheme = SplitUrl(server_url, /*default_port=*/-1, &host, &port);
  bool use_ssl = scheme == "https";
  if (port < 0) port = use_ssl ? 443 : 8000;
  TlsOptions tls;
  tls.use_ssl = use_ssl;
  tls.verify_peer = ssl_options.verify_peer;
  tls.verify_host = ssl_options.verify_host;
  tls.root_certificates = ssl_options.ca_info;
  tls.certificate_chain = ssl_options.cert;
  tls.private_key = ssl_options.key;
  client->reset(new InferenceServerHttpClient(host, port, verbose, tls));
  return Error::Success();
}

InferenceServerHttpClient::InferenceServerHttpClient(const std::string& host,
                                                     int port, bool verbose,
                                                     const TlsOptions& tls)
    : InferenceServerClient(verbose), host_(host), port_(port), tls_(tls) {}

InferenceServerHttpClient::~InferenceServerHttpClient() {
  {
    // Lock so the store can't slip between a worker's predicate check and
    // its block — an unsynchronized store + notify loses the wakeup and
    // join() below hangs.
    std::lock_guard<std::mutex> lk(async_mutex_);
    async_exit_ = true;
  }
  async_cv_.notify_all();
  for (auto& t : async_workers_) {
    if (t.joinable()) t.join();
  }
}

std::unique_ptr<HttpConnection> InferenceServerHttpClient::BorrowConnection() {
  std::lock_guard<std::mutex> lk(pool_mutex_);
  if (!pool_.empty()) {
    auto conn = std::move(pool_.front());
    pool_.pop_front();
    return conn;
  }
  return std::make_unique<HttpConnection>(host_, port_, tls_);
}

void InferenceServerHttpClient::ReturnConnection(
    std::unique_ptr<HttpConnection> conn) {
  std::lock_guard<std::mutex> lk(pool_mutex_);
  if (pool_.size() < 32) pool_.push_back(std::move(conn));
}

static std::string BuildHttpHead(const std::string& method,
                                 const std::string& path,
                                 const std::string& host,
                                 const Headers& headers, size_t body_len,
                                 size_t infer_header_len, bool has_ihcl) {
  std::string head;
  head.reserve(256);
  head += method + " " + path + " HTTP/1.1\r\n";
  head += "Host: " + host + "\r\n";
  head += "Content-Length: " + std::to_string(body_len) + "\r\n";
  if (has_ihcl) {
    head += "Inference-Header-Content-Length: " +
            std::to_string(infer_header_len) + "\r\n";
    head += "Content-Type: application/octet-stream\r\n";
  } else if (body_len > 0) {
    head += "Content-Type: application/json\r\n";
  }
  for (const auto& kv : headers) {
    head += kv.first + ": " + kv.second + "\r\n";
  }
  head += "\r\n";
  return head;
}

Error InferenceServerHttpClient::Get(const std::string& path, JsonPtr* response,
                                     const Headers& headers) {
  auto conn = BorrowConnection();
  std::string head = BuildHttpHead("GET", path, host_, headers, 0, 0, false);
  int status;
  Headers resp_headers;
  std::string body;
  Error err = conn->RoundTrip(head, {}, 0, &status, &resp_headers, &body);
  if (!err.IsOk()) return err;
  ReturnConnection(std::move(conn));
  if (response != nullptr && !body.empty()) {
    Error perr = Json::Parse(body, response);
    if (!perr.IsOk()) return perr;
  } else if (response != nullptr) {
    *response = Json::MakeObject();
  }
  if (status != 200) {
    std::string msg = "HTTP " + std::to_string(status);
    if (response && *response && (*response)->IsObject()) {
      JsonPtr e = (*response)->Get("error");
      if (e && e->IsString()) msg = e->AsString();
    }
    return Error(msg, status);
  }
  return Error::Success();
}

Error InferenceServerHttpClient::Post(const std::string& path,
                                      const std::string& body,
                                      JsonPtr* response,
                                      const Headers& headers) {
  auto conn = BorrowConnection();
  std::string head =
      BuildHttpHead("POST", path, host_, headers, body.size(), 0, false);
  std::vector<std::pair<const uint8_t*, size_t>> segs;
  if (!body.empty())
    segs.emplace_back(reinterpret_cast<const uint8_t*>(body.data()),
                      body.size());
  int status;
  Headers resp_headers;
  std::string resp_body;
  Error err =
      conn->RoundTrip(head, segs, 0, &status, &resp_headers, &resp_body);
  if (!err.IsOk()) return err;
  ReturnConnection(std::move(conn));
  JsonPtr parsed;
  if (!resp_body.empty()) {
    Error perr = Json::Parse(resp_body, &parsed);
    if (perr.IsOk() && response != nullptr) *response = parsed;
  }
  if (response != nullptr && *response == nullptr)
    *response = Json::MakeObject();
  if (status != 200) {
    std::string msg = "HTTP " + std::to_string(status);
    if (parsed && parsed->IsObject()) {
      JsonPtr e = parsed->Get("error");
      if (e && e->IsString()) msg = e->AsString();
    }
    return Error(msg, status);
  }
  return Error::Success();
}

// -- control plane ----------------------------------------------------------

Error InferenceServerHttpClient::IsServerLive(bool* live,
                                              const Headers& headers) {
  Error err = Get("/v2/health/live", nullptr, headers);
  *live = err.IsOk();
  return (err.StatusCode() >= 500 || err.IsOk()) ? Error::Success() : err;
}

Error InferenceServerHttpClient::IsServerReady(bool* ready,
                                               const Headers& headers) {
  Error err = Get("/v2/health/ready", nullptr, headers);
  *ready = err.IsOk();
  return Error::Success();
}

Error InferenceServerHttpClient::IsModelReady(bool* ready,
                                              const std::string& model_name,
                                              const std::string& model_version,
                                              const Headers& headers) {
  std::string path = "/v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  path += "/ready";
  Error err = Get(path, nullptr, headers);
  *ready = err.IsOk();
  return Error::Success();
}

Error InferenceServerHttpClient::ServerMetadata(JsonPtr* metadata,
                                                const Headers& headers) {
  return Get("/v2", metadata, headers);
}

Error InferenceServerHttpClient::ModelMetadata(JsonPtr* metadata,
                                               const std::string& model_name,
                                               const std::string& model_version,
                                               const Headers& headers) {
  std::string path = "/v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  return Get(path, metadata, headers);
}

Error InferenceServerHttpClient::ModelConfig(JsonPtr* config,
                                             const std::string& model_name,
                                             const std::string& model_version,
                                             const Headers& headers) {
  std::string path = "/v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  path += "/config";
  return Get(path, config, headers);
}

Error InferenceServerHttpClient::ModelRepositoryIndex(JsonPtr* index,
                                                      const Headers& headers) {
  return Post("/v2/repository/index", "", index, headers);
}

Error InferenceServerHttpClient::LoadModel(const std::string& model_name,
                                           const Headers& headers,
                                           const std::string& config) {
  std::string body;
  if (!config.empty()) {
    auto obj = Json::MakeObject();
    auto params = Json::MakeObject();
    params->Set("config", config);
    obj->Set("parameters", params);
    body = obj->Serialize();
  }
  return Post("/v2/repository/models/" + model_name + "/load", body, nullptr,
              headers);
}

Error InferenceServerHttpClient::UnloadModel(const std::string& model_name,
                                             const Headers& headers) {
  return Post("/v2/repository/models/" + model_name + "/unload", "", nullptr,
              headers);
}

Error InferenceServerHttpClient::ModelInferenceStatistics(
    JsonPtr* infer_stat, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path = "/v2/models";
  if (!model_name.empty()) {
    path += "/" + model_name;
    if (!model_version.empty()) path += "/versions/" + model_version;
  }
  path += "/stats";
  return Get(path, infer_stat, headers);
}

// -- shared memory ----------------------------------------------------------

Error InferenceServerHttpClient::SystemSharedMemoryStatus(
    JsonPtr* status, const std::string& region_name, const Headers& headers) {
  std::string path = "/v2/systemsharedmemory";
  if (!region_name.empty()) path += "/region/" + region_name;
  path += "/status";
  return Get(path, status, headers);
}

Error InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& headers) {
  auto obj = Json::MakeObject();
  obj->Set("key", key);
  obj->Set("offset", static_cast<uint64_t>(offset));
  obj->Set("byte_size", static_cast<uint64_t>(byte_size));
  return Post("/v2/systemsharedmemory/region/" + name + "/register",
              obj->Serialize(), nullptr, headers);
}

Error InferenceServerHttpClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path = "/v2/systemsharedmemory";
  if (!name.empty()) path += "/region/" + name;
  path += "/unregister";
  return Post(path, "", nullptr, headers);
}

Error InferenceServerHttpClient::TpuSharedMemoryStatus(
    JsonPtr* status, const std::string& region_name, const Headers& headers) {
  std::string path = "/v2/tpusharedmemory";
  if (!region_name.empty()) path += "/region/" + region_name;
  path += "/status";
  return Get(path, status, headers);
}

Error InferenceServerHttpClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, size_t byte_size,
    int device_id, const Headers& headers) {
  auto obj = Json::MakeObject();
  obj->Set("raw_handle", Json::MakeObject());
  obj->Get("raw_handle")->Set("b64", Base64Encode(raw_handle));
  obj->Set("device_id", static_cast<int64_t>(device_id));
  obj->Set("byte_size", static_cast<uint64_t>(byte_size));
  return Post("/v2/tpusharedmemory/region/" + name + "/register",
              obj->Serialize(), nullptr, headers);
}

Error InferenceServerHttpClient::UnregisterTpuSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path = "/v2/tpusharedmemory";
  if (!name.empty()) path += "/region/" + name;
  path += "/unregister";
  return Post(path, "", nullptr, headers);
}

// -- inference --------------------------------------------------------------

Error InferenceServerHttpClient::PrepareInferRequest(
    PreparedRequest* prep, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  prep->path = "/v2/models/" + options.model_name;
  if (!options.model_version.empty())
    prep->path += "/versions/" + options.model_version;
  prep->path += "/infer";
  prep->timeout_us = options.client_timeout_us;

  auto head = Json::MakeObject();
  if (!options.request_id.empty()) head->Set("id", options.request_id);

  auto params = Json::MakeObject();
  if (options.sequence_id != 0) {
    params->Set("sequence_id", options.sequence_id);
    params->Set("sequence_start", options.sequence_start);
    params->Set("sequence_end", options.sequence_end);
  }
  if (options.priority != 0) params->Set("priority", options.priority);
  if (options.server_timeout_us != 0)
    params->Set("timeout", options.server_timeout_us);
  for (const auto& kv : options.int_parameters)
    params->Set(kv.first, kv.second);
  for (const auto& kv : options.string_parameters)
    params->Set(kv.first, kv.second);
  for (const auto& kv : options.bool_parameters)
    params->Set(kv.first, kv.second);
  // With no explicit output list, ask for all outputs as binary tails
  // rather than JSON data arrays (reference `binary_data_output` request
  // parameter, http_client.cc:334).
  if (outputs.empty()) params->Set("binary_data_output", true);
  if (!params->Members().empty()) head->Set("parameters", params);

  auto jinputs = Json::MakeArray();
  for (const InferInput* input : inputs) {
    auto jin = Json::MakeObject();
    jin->Set("name", input->Name());
    auto shape = Json::MakeArray();
    for (int64_t d : input->Shape()) shape->Append(Json::MakeInt(d));
    jin->Set("shape", shape);
    jin->Set("datatype", input->Datatype());
    auto iparams = Json::MakeObject();
    if (input->IsSharedMemory()) {
      iparams->Set("shared_memory_region", input->SharedMemoryName());
      iparams->Set("shared_memory_byte_size",
                   static_cast<uint64_t>(input->SharedMemoryByteSize()));
      if (input->SharedMemoryOffset() != 0)
        iparams->Set("shared_memory_offset",
                     static_cast<uint64_t>(input->SharedMemoryOffset()));
    } else {
      iparams->Set("binary_data_size",
                   static_cast<uint64_t>(input->TotalByteSize()));
      for (const auto& seg : input->Buffers()) prep->tail.push_back(seg);
    }
    jin->Set("parameters", iparams);
    jinputs->Append(jin);
  }
  head->Set("inputs", jinputs);

  if (!outputs.empty()) {
    auto joutputs = Json::MakeArray();
    for (const InferRequestedOutput* output : outputs) {
      auto jout = Json::MakeObject();
      jout->Set("name", output->Name());
      auto oparams = Json::MakeObject();
      if (output->IsSharedMemory()) {
        oparams->Set("shared_memory_region", output->SharedMemoryName());
        oparams->Set("shared_memory_byte_size",
                     static_cast<uint64_t>(output->SharedMemoryByteSize()));
        if (output->SharedMemoryOffset() != 0)
          oparams->Set("shared_memory_offset",
                       static_cast<uint64_t>(output->SharedMemoryOffset()));
      } else {
        if (output->BinaryData()) oparams->Set("binary_data", true);
        if (output->ClassCount() > 0)
          oparams->Set("classification",
                       static_cast<uint64_t>(output->ClassCount()));
      }
      if (!oparams->Members().empty()) jout->Set("parameters", oparams);
      joutputs->Append(jout);
    }
    head->Set("outputs", joutputs);
  }

  prep->json_head = head->Serialize();
  prep->header_length = prep->json_head.size();
  prep->total_body = prep->header_length;
  for (const auto& seg : prep->tail) prep->total_body += seg.second;
  return Error::Success();
}

Error InferenceServerHttpClient::DoInfer(HttpConnection* conn,
                                         const PreparedRequest& prep,
                                         const Headers& headers,
                                         RequestTimers* timers,
                                         InferResult** result) {
  Headers all_headers = headers;
  if (!prep.content_encoding.empty())
    all_headers["Content-Encoding"] = prep.content_encoding;
  if (!prep.accept_encoding.empty())
    all_headers["Accept-Encoding"] = prep.accept_encoding;
  std::string http_head =
      BuildHttpHead("POST", prep.path, host_, all_headers, prep.total_body,
                    prep.header_length, true);
  std::vector<std::pair<const uint8_t*, size_t>> segs;
  if (!prep.content_encoding.empty()) {
    segs.emplace_back(
        reinterpret_cast<const uint8_t*>(prep.compressed.data()),
        prep.compressed.size());
  } else {
    segs.emplace_back(reinterpret_cast<const uint8_t*>(prep.json_head.data()),
                      prep.json_head.size());
    for (const auto& seg : prep.tail) segs.push_back(seg);
  }

  int status;
  Headers resp_headers;
  std::string body;
  Error err = conn->RoundTrip(http_head, segs, prep.timeout_us, &status,
                              &resp_headers, &body, timers);
  if (!err.IsOk()) return err;

  auto ce = resp_headers.find("content-encoding");
  if (ce != resp_headers.end() && !ce->second.empty() &&
      ce->second != "identity") {
    std::string plain;
    err = zutil::Inflate(body, &plain);
    if (!err.IsOk()) {
      return Error("response decompression failed: " + err.Message(), 400);
    }
    body.swap(plain);
  }

  size_t header_length = 0;
  auto it = resp_headers.find("inference-header-content-length");
  if (it != resp_headers.end()) header_length = atoll(it->second.c_str());
  return InferResultHttp::Create(result, std::move(body), header_length,
                                 status);
}

Error InferenceServerHttpClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, CompressionType request_compression_algorithm,
    CompressionType response_compression_algorithm) {
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);

  PreparedRequest prep;
  Error err = PrepareInferRequest(&prep, options, inputs, outputs);
  if (!err.IsOk()) return err;
  err = CompressRequest(&prep, request_compression_algorithm);
  if (!err.IsOk()) return err;
  if (response_compression_algorithm == CompressionType::GZIP)
    prep.accept_encoding = "gzip";
  else if (response_compression_algorithm == CompressionType::DEFLATE)
    prep.accept_encoding = "deflate";

  auto conn = BorrowConnection();
  err = DoInfer(conn.get(), prep, headers, &timers, result);
  if (!err.IsOk()) return err;
  ReturnConnection(std::move(conn));

  timers.Capture(RequestTimers::Kind::REQUEST_END);
  UpdateInferStat(timers);
  return Error::Success();
}

Error InferenceServerHttpClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, CompressionType request_compression_algorithm,
    CompressionType response_compression_algorithm) {
  if (callback == nullptr)
    return Error("callback is required for AsyncInfer", 400);

  auto job = std::make_unique<AsyncJob>();
  Error err = PrepareInferRequest(&job->prep, options, inputs, outputs);
  if (!err.IsOk()) return err;
  err = CompressRequest(&job->prep, request_compression_algorithm);
  if (!err.IsOk()) return err;
  if (response_compression_algorithm == CompressionType::GZIP)
    job->prep.accept_encoding = "gzip";
  else if (response_compression_algorithm == CompressionType::DEFLATE)
    job->prep.accept_encoding = "deflate";
  job->headers = headers;
  job->callback = std::move(callback);

  // Copy tail segments so callers may free inputs immediately.
  size_t tail_size = 0;
  for (const auto& seg : job->prep.tail) tail_size += seg.second;
  job->body_copy.reserve(tail_size);
  for (const auto& seg : job->prep.tail)
    job->body_copy.append(reinterpret_cast<const char*>(seg.first),
                          seg.second);
  job->prep.tail.clear();
  if (!job->body_copy.empty())
    job->prep.tail.emplace_back(
        reinterpret_cast<const uint8_t*>(job->body_copy.data()),
        job->body_copy.size());

  {
    std::lock_guard<std::mutex> lk(async_mutex_);
    async_queue_.push(std::move(job));
    size_t wanted = std::min(max_async_workers_,
                             async_workers_.size() + async_queue_.size());
    while (async_workers_.size() < wanted) {
      async_workers_.emplace_back(
          [this]() { AsyncWorkerLoop(); });
    }
  }
  async_cv_.notify_one();
  return Error::Success();
}

void InferenceServerHttpClient::AsyncWorkerLoop() {
  // Each worker owns one keep-alive connection; one in-flight request per
  // worker gives up to max_async_workers_ concurrent requests.
  HttpConnection conn(host_, port_, tls_);
  while (true) {
    std::unique_ptr<AsyncJob> job;
    {
      std::unique_lock<std::mutex> lk(async_mutex_);
      async_cv_.wait(lk,
                     [this]() { return async_exit_ || !async_queue_.empty(); });
      if (async_exit_ && async_queue_.empty()) return;
      job = std::move(async_queue_.front());
      async_queue_.pop();
    }
    RequestTimers timers;
    timers.Capture(RequestTimers::Kind::REQUEST_START);
    InferResult* result = nullptr;
    Error err = DoInfer(&conn, job->prep, job->headers, &timers, &result);
    timers.Capture(RequestTimers::Kind::REQUEST_END);
    if (err.IsOk()) {
      UpdateInferStat(timers);
    }
    if (result == nullptr) {
      // Build a minimal error result so callbacks always receive one. The
      // message goes through the JSON serializer: raw concatenation breaks
      // on quotes/backslashes in server-echoed error text and would leave
      // the callback holding nullptr.
      auto err_obj = Json::MakeObject();
      err_obj->Set("error", err.Message());
      InferResultHttp::Create(&result, err_obj->Serialize(), 0,
                              err.StatusCode() ? err.StatusCode() : 400);
    }
    job->callback(result);
  }
}

}  // namespace tpuclient
