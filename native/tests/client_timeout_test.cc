// Client-timeout integration test: sync/async/streaming infer over both
// protocols with microsecond client deadlines must surface timeout errors
// (status 499), and generous deadlines must succeed with validated values.
//
// Reference counterpart: client_timeout_test.cc:391 (drives model `simple`
// over HTTP+gRPC with tiny timeouts, asserting "Deadline Exceeded";
// ValidateShapeAndDatatype/ValidateResult oracle at :48-103).
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <iostream>
#include <mutex>

#include "tpuclient/grpc_client.h"
#include "tpuclient/http_client.h"

namespace tc = tpuclient;

static int failures = 0;
#define CHECK(cond, what)                                   \
  do {                                                      \
    if (!(cond)) {                                          \
      std::cerr << "FAIL: " << what << std::endl;           \
      ++failures;                                           \
    }                                                       \
  } while (false)

namespace {

std::vector<int32_t> g_input0(16), g_input1(16);

void BuildInputs(tc::InferInput** input0, tc::InferInput** input1) {
  for (int i = 0; i < 16; ++i) {
    g_input0[i] = i;
    g_input1[i] = 1;
  }
  tc::InferInput::Create(input0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(input1, "INPUT1", {1, 16}, "INT32");
  (*input0)->AppendRaw(reinterpret_cast<uint8_t*>(g_input0.data()),
                       16 * sizeof(int32_t));
  (*input1)->AppendRaw(reinterpret_cast<uint8_t*>(g_input1.data()),
                       16 * sizeof(int32_t));
}

// Validates OUTPUT0=a+b on a successful result (reference ValidateResult).
bool ValidateResult(tc::InferResult* result) {
  if (!result->RequestStatus().IsOk()) return false;
  std::vector<int64_t> shape;
  std::string dtype;
  if (!result->Shape("OUTPUT0", &shape).IsOk() ||
      !result->Datatype("OUTPUT0", &dtype).IsOk()) {
    return false;
  }
  if (shape != std::vector<int64_t>({1, 16}) || dtype != "INT32") {
    return false;
  }
  const uint8_t* buf;
  size_t n;
  if (!result->RawData("OUTPUT0", &buf, &n).IsOk() ||
      n != 16 * sizeof(int32_t)) {
    return false;
  }
  const int32_t* vals = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (vals[i] != g_input0[i] + g_input1[i]) return false;
  }
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  std::string http_url = "localhost:8000";
  std::string grpc_url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:g:")) != -1) {
    if (opt == 'u') http_url = optarg;
    if (opt == 'g') grpc_url = optarg;
  }

  tc::InferInput *input0, *input1;
  BuildInputs(&input0, &input1);
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);

  // ---- HTTP sync: tiny timeout fails with 499, generous succeeds --------
  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    CHECK(tc::InferenceServerHttpClient::Create(&client, http_url).IsOk(),
          "http client create");
    tc::InferOptions options("simple");
    options.client_timeout_us = 1;  // microsecond deadline: must fail
    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, {input0, input1});
    CHECK(!err.IsOk() && err.StatusCode() == 499,
          "http sync tiny timeout -> 499 (got " + err.Message() + ")");
    delete result;

    options.client_timeout_us = 60 * 1000 * 1000;
    result = nullptr;
    err = client->Infer(&result, options, {input0, input1});
    CHECK(err.IsOk(), "http sync generous timeout succeeds");
    if (err.IsOk()) {
      CHECK(ValidateResult(result), "http sync result values");
      delete result;
    }
  }

  // ---- gRPC sync ---------------------------------------------------------
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK(tc::InferenceServerGrpcClient::Create(&client, grpc_url).IsOk(),
          "grpc client create");
    tc::InferOptions options("simple");
    options.client_timeout_us = 1;
    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, {input0, input1});
    CHECK(!err.IsOk() && err.StatusCode() == 499,
          "grpc sync tiny timeout -> 499 (got " + err.Message() + ")");
    delete result;

    options.client_timeout_us = 60 * 1000 * 1000;
    result = nullptr;
    err = client->Infer(&result, options, {input0, input1});
    CHECK(err.IsOk(), "grpc sync generous timeout succeeds");
    if (err.IsOk()) {
      CHECK(ValidateResult(result), "grpc sync result values");
      delete result;
    }
  }

  // ---- gRPC async: generous deadline completes with valid values --------
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK(tc::InferenceServerGrpcClient::Create(&client, grpc_url).IsOk(),
          "grpc async client create");
    tc::InferOptions options("simple");
    options.client_timeout_us = 60 * 1000 * 1000;
    std::mutex mtx;
    std::condition_variable cv;
    bool done = false, ok = false;
    tc::Error err = client->AsyncInfer(
        [&](tc::InferResult* result) {
          std::unique_ptr<tc::InferResult> owner(result);
          std::lock_guard<std::mutex> lk(mtx);
          ok = ValidateResult(result);
          done = true;
          cv.notify_all();
        },
        options, {input0, input1});
    CHECK(err.IsOk(), "grpc async submit");
    std::unique_lock<std::mutex> lk(mtx);
    CHECK(cv.wait_for(lk, std::chrono::seconds(120), [&] { return done; }),
          "grpc async completion");
    CHECK(ok, "grpc async result values");
  }

  // ---- gRPC streaming: request on stream completes and validates --------
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK(tc::InferenceServerGrpcClient::Create(&client, grpc_url).IsOk(),
          "grpc stream client create");
    std::mutex mtx;
    std::condition_variable cv;
    bool done = false, ok = false;
    tc::Error err = client->StartStream([&](tc::InferResult* result) {
      std::unique_ptr<tc::InferResult> owner(result);
      std::lock_guard<std::mutex> lk(mtx);
      ok = ValidateResult(result);
      done = true;
      cv.notify_all();
    });
    CHECK(err.IsOk(), "grpc stream start");
    tc::InferOptions options("simple");
    CHECK(client->AsyncStreamInfer(options, {input0, input1}).IsOk(),
          "grpc stream submit");
    {
      std::unique_lock<std::mutex> lk(mtx);
      CHECK(cv.wait_for(lk, std::chrono::seconds(120), [&] { return done; }),
            "grpc stream completion");
      CHECK(ok, "grpc stream result values");
    }
    client->StopStream();
  }

  if (failures == 0) {
    std::cout << "PASS : client_timeout_test" << std::endl;
    return 0;
  }
  std::cerr << failures << " FAILURES" << std::endl;
  return 1;
}
