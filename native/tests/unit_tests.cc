// Unit tests for the dependency-free core: JSON DOM, base64, BYTES codec,
// shm utils, InferInput scatter-gather. No server required (SURVEY.md §4:
// the reference has no unit suite; this framework's test pyramid starts
// with codec-level units).
#include <cassert>
#include <cstdio>
#include <cstring>

#include "tpuclient/base64.h"
#include "tpuclient/common.h"
#include "tpuclient/json.h"
#include "tpuclient/shm_utils.h"

#include "../src/h2.h"

using namespace tpuclient;

static int failures = 0;
#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      ++failures;                                                      \
    }                                                                  \
  } while (0)

static void TestJsonRoundTrip() {
  const char* text =
      "{\"name\":\"simple\",\"ready\":true,\"n\":-42,\"u\":18446744073709551615,"
      "\"pi\":3.5,\"arr\":[1,2,3],\"nested\":{\"s\":\"a\\nb\\u0041\"},"
      "\"nil\":null}";
  JsonPtr j;
  Error err = Json::Parse(text, strlen(text), &j);
  CHECK(err.IsOk());
  CHECK(j->IsObject());
  CHECK(j->Get("name")->AsString() == "simple");
  CHECK(j->Get("ready")->AsBool());
  CHECK(j->Get("n")->AsInt() == -42);
  CHECK(j->Get("u")->AsUint() == 18446744073709551615ULL);
  CHECK(j->Get("pi")->AsDouble() == 3.5);
  CHECK(j->Get("arr")->Size() == 3);
  CHECK(j->Get("arr")->At(2)->AsInt() == 3);
  CHECK(j->Get("nested")->Get("s")->AsString() == "a\nbA");
  CHECK(j->Get("nil")->IsNull());

  // serialize → reparse fixpoint
  std::string ser = j->Serialize();
  JsonPtr j2;
  CHECK(Json::Parse(ser, &j2).IsOk());
  CHECK(j2->Get("u")->AsUint() == 18446744073709551615ULL);
  CHECK(j2->Serialize() == ser);

  // failures
  JsonPtr bad;
  CHECK(!Json::Parse("{not json", 9, &bad).IsOk());
  CHECK(!Json::Parse("[1,2", 4, &bad).IsOk());
  CHECK(!Json::Parse("{}trailing", 10, &bad).IsOk());
  CHECK(Json::Parse("\"\\ud83d\\ude00\"", 14, &bad).IsOk());  // 😀 surrogate
  CHECK(bad->AsString() == "\xF0\x9F\x98\x80");
}

static void TestBase64() {
  const uint8_t data[] = {0x00, 0x01, 0xFE, 0xFF, 0x7F};
  for (size_t n = 0; n <= sizeof(data); ++n) {
    std::string enc = Base64Encode(data, n);
    std::vector<uint8_t> dec;
    CHECK(Base64Decode(enc, &dec));
    CHECK(dec.size() == n);
    CHECK(memcmp(dec.data(), data, n) == 0);
  }
  CHECK(Base64Encode(reinterpret_cast<const uint8_t*>("hello"), 5) ==
        "aGVsbG8=");
  std::vector<uint8_t> dec;
  CHECK(!Base64Decode("a!b", &dec));
}

static void TestBytesCodec() {
  std::vector<std::string> strings = {"", "a", "hello world",
                                      std::string("\x00\x01", 2)};
  std::string buf;
  SerializeStringTensor(strings, &buf);
  CHECK(buf.size() == 4 * 4 + 0 + 1 + 11 + 2);
  std::vector<std::string> out;
  Error err = DeserializeStringTensor(
      reinterpret_cast<const uint8_t*>(buf.data()), buf.size(), &out);
  CHECK(err.IsOk());
  CHECK(out == strings);

  // truncated payload must fail, not crash
  out.clear();
  err = DeserializeStringTensor(reinterpret_cast<const uint8_t*>(buf.data()),
                                buf.size() - 1, &out);
  CHECK(!err.IsOk());
}

static void TestInferInput() {
  InferInput* input;
  CHECK(InferInput::Create(&input, "INPUT0", {2, 16}, "INT32").IsOk());
  int32_t a[16], b[16];
  for (int i = 0; i < 16; ++i) {
    a[i] = i;
    b[i] = 100 + i;
  }
  CHECK(input->AppendRaw(reinterpret_cast<uint8_t*>(a), sizeof(a)).IsOk());
  CHECK(input->AppendRaw(reinterpret_cast<uint8_t*>(b), sizeof(b)).IsOk());
  CHECK(input->TotalByteSize() == 128);
  CHECK(input->Buffers().size() == 2);
  std::string concat;
  input->CopyTo(&concat);
  CHECK(concat.size() == 128);
  CHECK(memcmp(concat.data(), a, 64) == 0);
  CHECK(memcmp(concat.data() + 64, b, 64) == 0);
  // shm and raw are mutually exclusive
  CHECK(!input->SetSharedMemory("region", 128).IsOk());
  CHECK(input->Reset().IsOk());
  CHECK(input->SetSharedMemory("region", 128).IsOk());
  CHECK(!input->AppendRaw(reinterpret_cast<uint8_t*>(a), 64).IsOk());
  delete input;

  InferRequestedOutput* output;
  CHECK(InferRequestedOutput::Create(&output, "OUTPUT0", 3).IsOk());
  CHECK(output->ClassCount() == 3);
  CHECK(output->SetSharedMemory("region", 64).IsOk());
  CHECK(output->IsSharedMemory());
  CHECK(output->UnsetSharedMemory().IsOk());
  CHECK(!output->IsSharedMemory());
  delete output;
}

static void TestShmUtils() {
  const char* key = "/tpuclient_unit_shm";
  int fd;
  CHECK(CreateSharedMemoryRegion(key, 4096, &fd).IsOk());
  void* addr;
  CHECK(MapSharedMemory(fd, 0, 4096, &addr).IsOk());
  memset(addr, 0xAB, 4096);
  // second mapping sees the data
  int fd2;
  CHECK(CreateSharedMemoryRegion(key, 4096, &fd2).IsOk());
  void* addr2;
  CHECK(MapSharedMemory(fd2, 0, 4096, &addr2).IsOk());
  CHECK(memcmp(addr, addr2, 4096) == 0);
  CHECK(UnmapSharedMemory(addr, 4096).IsOk());
  CHECK(UnmapSharedMemory(addr2, 4096).IsOk());
  CHECK(CloseSharedMemory(fd).IsOk());
  CHECK(CloseSharedMemory(fd2).IsOk());
  CHECK(UnlinkSharedMemoryRegion(key).IsOk());
  CHECK(!UnlinkSharedMemoryRegion(key).IsOk());  // already gone
}

static void TestDtypes() {
  CHECK(DtypeByteSize("INT32") == 4);
  CHECK(DtypeByteSize("FP64") == 8);
  CHECK(DtypeByteSize("BF16") == 2);
  CHECK(DtypeByteSize("BOOL") == 1);
  CHECK(DtypeByteSize("BYTES") == 0);
  CHECK(ElementCount({2, 3, 4}) == 24);
  CHECK(ElementCount({2, -1}) == -1);
}

static void TestSanitizeForLog() {
  // Peer bytes in diagnostics: non-printables masked, length capped.
  CHECK(SanitizeForLog("plain ascii") == "plain ascii");
  CHECK(SanitizeForLog(std::string("\x00\xff ok\x1b[31m", 10)) == ".. ok.[31m");
  std::string longs(100, 'a');
  std::string out = SanitizeForLog(longs, 8);
  CHECK(out == "aaaaaaaa...");
}

static void TestHuffman() {
  // Round-trip through the RFC 7541 Appendix B codes (table generated and
  // verified against libnghttp2 by tools/gen_hpack_table.py).
  for (const std::string& s :
       {std::string("www.example.com"), std::string(""),
        std::string("application/grpc"), std::string("\x00\xff\x01\xfe", 4),
        std::string(256, '\x07')}) {
    std::string enc, dec;
    h2::HuffmanEncode(s, &enc);
    CHECK(h2::HuffmanDecode(reinterpret_cast<const uint8_t*>(enc.data()),
                            enc.size(), &dec)
              .IsOk());
    CHECK(dec == s);
  }
  // RFC 7541 C.4.1: "www.example.com" huffman-encodes to these 12 bytes.
  std::string enc;
  h2::HuffmanEncode("www.example.com", &enc);
  const uint8_t expect[] = {0xf1, 0xe3, 0xc2, 0xe5, 0xf2, 0x3a,
                            0x6b, 0xa0, 0xab, 0x90, 0xf4, 0xff};
  CHECK(enc.size() == sizeof(expect));
  CHECK(memcmp(enc.data(), expect, sizeof(expect)) == 0);
  // Bad padding must be rejected: 0x00 = symbol '0' (code 00000) followed
  // by three zero padding bits — RFC 7541 §5.2 requires padding be the
  // all-ones EOS prefix.
  const uint8_t bad[] = {0x00};
  std::string out;
  CHECK(!h2::HuffmanDecode(bad, 1, &out).IsOk());
}

static void TestHpack() {
  // Our encoder's output must decode back through the full decoder.
  h2::HeaderList in = {
      {":method", "POST"},
      {":path", "/inference.GRPCInferenceService/ModelInfer"},
      {"content-type", "application/grpc"},
      {"x-empty", ""},
  };
  std::string block;
  h2::HpackEncode(in, &block);
  h2::HpackDecoder dec;
  h2::HeaderList out;
  CHECK(dec.Decode(reinterpret_cast<const uint8_t*>(block.data()),
                   block.size(), &out)
            .IsOk());
  CHECK(out == in);

  // RFC 7541 C.3: three request header blocks on one decoder exercising
  // indexed fields, incremental indexing, and dynamic-table references.
  h2::HpackDecoder rfc;
  {
    const uint8_t block1[] = {0x82, 0x86, 0x84, 0x41, 0x0f, 0x77, 0x77, 0x77,
                              0x2e, 0x65, 0x78, 0x61, 0x6d, 0x70, 0x6c, 0x65,
                              0x2e, 0x63, 0x6f, 0x6d};
    h2::HeaderList h;
    CHECK(rfc.Decode(block1, sizeof(block1), &h).IsOk());
    h2::HeaderList expect1 = {{":method", "GET"},
                              {":scheme", "http"},
                              {":path", "/"},
                              {":authority", "www.example.com"}};
    CHECK(h == expect1);
  }
  {
    const uint8_t block2[] = {0x82, 0x86, 0x84, 0xbe, 0x58, 0x08, 0x6e, 0x6f,
                              0x2d, 0x63, 0x61, 0x63, 0x68, 0x65};
    h2::HeaderList h;
    CHECK(rfc.Decode(block2, sizeof(block2), &h).IsOk());
    h2::HeaderList expect2 = {{":method", "GET"},
                              {":scheme", "http"},
                              {":path", "/"},
                              {":authority", "www.example.com"},
                              {"cache-control", "no-cache"}};
    CHECK(h == expect2);
  }
  {
    // Third block switches to https/index.html and adds a custom pair via
    // huffman-free literals; dynamic entries from prior blocks resolve.
    const uint8_t block3[] = {0x82, 0x87, 0x85, 0xbf, 0x40, 0x0a, 0x63, 0x75,
                              0x73, 0x74, 0x6f, 0x6d, 0x2d, 0x6b, 0x65, 0x79,
                              0x0c, 0x63, 0x75, 0x73, 0x74, 0x6f, 0x6d, 0x2d,
                              0x76, 0x61, 0x6c, 0x75, 0x65};
    h2::HeaderList h;
    CHECK(rfc.Decode(block3, sizeof(block3), &h).IsOk());
    h2::HeaderList expect3 = {{":method", "GET"},
                              {":scheme", "https"},
                              {":path", "/index.html"},
                              {":authority", "www.example.com"},
                              {"custom-key", "custom-value"}};
    CHECK(h == expect3);
  }
  {
    // RFC 7541 §6.3: a Dynamic Table Size Update above the decoder's
    // configured limit is a connection error, not an allocation grant.
    h2::HpackDecoder small(64);
    // Update to exactly the configured limit (5-bit prefix: 31 + 33 = 64)
    // must be accepted — guards the > vs >= boundary.
    const uint8_t shrink[] = {0x3f, 0x21, 0x82};  // update to 64, then GET
    h2::HeaderList h;
    CHECK(small.Decode(shrink, sizeof(shrink), &h).IsOk());
    // 5-bit prefix int 8192 = 0x3f followed by varint(8192-31)
    const uint8_t grow[] = {0x3f, 0xe1, 0x3f, 0x82};
    h2::HeaderList h2l;
    CHECK(!small.Decode(grow, sizeof(grow), &h2l).IsOk());
  }
}

int main() {
  TestJsonRoundTrip();
  TestBase64();
  TestBytesCodec();
  TestInferInput();
  TestShmUtils();
  TestDtypes();
  TestSanitizeForLog();
  TestHuffman();
  TestHpack();
  if (failures == 0) {
    printf("ALL UNIT TESTS PASSED\n");
    return 0;
  }
  printf("%d FAILURES\n", failures);
  return 1;
}
