// Memory-leak integration test: many inferences with or without object
// reuse; fails if process RSS keeps climbing after steady state.
//
// Reference counterpart: memory_leak_test.cc:301 (`repetitions` inferences
// with optional object `reuse`, RunSynchronousInference :109-175), paired
// with the Python memory_growth_test.
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <vector>

#include "tpuclient/grpc_client.h"
#include "tpuclient/http_client.h"

namespace tc = tpuclient;

namespace {

long RssKb() {
  FILE* f = fopen("/proc/self/status", "r");
  if (f == nullptr) return 0;
  char line[256];
  long kb = 0;
  while (fgets(line, sizeof(line), f) != nullptr) {
    if (strncmp(line, "VmRSS:", 6) == 0) {
      sscanf(line + 6, "%ld", &kb);
      break;
    }
  }
  fclose(f);
  return kb;
}

template <typename Client>
int RunLoop(Client* client, int repetitions, bool reuse, long max_growth_kb,
            const char* label) {
  std::vector<int32_t> a(16), b(16);
  for (int i = 0; i < 16; ++i) {
    a[i] = i;
    b[i] = 1;
  }

  auto make_inputs = [&](tc::InferInput** i0, tc::InferInput** i1) {
    tc::InferInput::Create(i0, "INPUT0", {1, 16}, "INT32");
    tc::InferInput::Create(i1, "INPUT1", {1, 16}, "INT32");
    (*i0)->AppendRaw(reinterpret_cast<uint8_t*>(a.data()), 64);
    (*i1)->AppendRaw(reinterpret_cast<uint8_t*>(b.data()), 64);
  };

  tc::InferInput *ri0 = nullptr, *ri1 = nullptr;
  if (reuse) make_inputs(&ri0, &ri1);
  tc::InferOptions options("simple");

  auto one = [&]() -> bool {
    tc::InferInput *i0 = ri0, *i1 = ri1;
    if (!reuse) make_inputs(&i0, &i1);
    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, {i0, i1});
    bool ok = err.IsOk() && result->RequestStatus().IsOk();
    delete result;
    if (!reuse) {
      delete i0;
      delete i1;
    }
    return ok;
  };

  // Warmup to allocator steady state, then measure.
  for (int i = 0; i < 100; ++i) {
    if (!one()) {
      std::cerr << label << ": warmup inference failed" << std::endl;
      return 1;
    }
  }
  long base = RssKb();
  for (int i = 0; i < repetitions; ++i) {
    if (!one()) {
      std::cerr << label << ": inference " << i << " failed" << std::endl;
      return 1;
    }
  }
  long growth = RssKb() - base;
  std::cout << label << " (reuse=" << reuse << "): RSS growth " << growth
            << " kB over " << repetitions << " inferences" << std::endl;
  if (growth > max_growth_kb) {
    std::cerr << label << ": FAIL, growth " << growth << " kB > "
              << max_growth_kb << " kB" << std::endl;
    return 1;
  }
  if (reuse) {
    delete ri0;
    delete ri1;
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string http_url = "localhost:8000";
  std::string grpc_url = "localhost:8001";
  int repetitions = 1000;
  long max_growth_kb = 20 * 1024;
  int opt;
  while ((opt = getopt(argc, argv, "u:g:r:")) != -1) {
    if (opt == 'u') http_url = optarg;
    if (opt == 'g') grpc_url = optarg;
    if (opt == 'r') repetitions = atoi(optarg);
  }

  int rc = 0;
  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    if (!tc::InferenceServerHttpClient::Create(&client, http_url).IsOk()) {
      std::cerr << "http client create failed" << std::endl;
      return 1;
    }
    rc |= RunLoop(client.get(), repetitions, /*reuse=*/true, max_growth_kb,
                  "http");
    rc |= RunLoop(client.get(), repetitions, /*reuse=*/false, max_growth_kb,
                  "http");
  }
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    if (!tc::InferenceServerGrpcClient::Create(&client, grpc_url).IsOk()) {
      std::cerr << "grpc client create failed" << std::endl;
      return 1;
    }
    rc |= RunLoop(client.get(), repetitions, /*reuse=*/true, max_growth_kb,
                  "grpc");
    rc |= RunLoop(client.get(), repetitions, /*reuse=*/false, max_growth_kb,
                  "grpc");
  }

  if (rc == 0) {
    std::cout << "PASS : memory_leak_test" << std::endl;
  }
  return rc;
}
