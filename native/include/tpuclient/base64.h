// base64 codec, used to carry opaque TPU-region handles over the HTTP
// control plane (same role the vendored libb64 plays for CUDA-IPC handles in
// the reference, /root/reference/src/c++/library/http_client.cc:108-119).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace tpuclient {

std::string Base64Encode(const uint8_t* data, size_t len);
inline std::string Base64Encode(const std::string& s) {
  return Base64Encode(reinterpret_cast<const uint8_t*>(s.data()), s.size());
}
bool Base64Decode(const std::string& text, std::vector<uint8_t>* out);

}  // namespace tpuclient
