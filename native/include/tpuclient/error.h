// Error type for the C++ client library.
//
// Same role as the reference's triton::client::Error
// (/root/reference/src/c++/library/common.h:60-82): a value type carrying
// success/failure plus a message, returned by every client call. Ours also
// carries the HTTP status (or 0) so callers can distinguish timeout (499)
// from protocol errors without string matching.
#pragma once

#include <ostream>
#include <string>

namespace tpuclient {

class Error {
 public:
  Error() : ok_(true), status_(0) {}
  explicit Error(std::string msg, int status = 0)
      : ok_(false), msg_(std::move(msg)), status_(status) {}

  static Error Success() { return Error(); }

  bool IsOk() const { return ok_; }
  const std::string& Message() const { return msg_; }
  int StatusCode() const { return status_; }

  friend std::ostream& operator<<(std::ostream& out, const Error& err) {
    if (err.ok_) {
      out << "OK";
    } else {
      out << err.msg_;
      if (err.status_ != 0) out << " (status " << err.status_ << ")";
    }
    return out;
  }

 private:
  bool ok_;
  std::string msg_;
  int status_;
};

}  // namespace tpuclient
