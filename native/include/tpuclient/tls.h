// TLS client transport shim shared by the HTTP/1.1 and HTTP/2 clients.
//
// Plays the role libcurl's TLS integration and grpc++'s SslCredentials play
// for the reference clients (https URLs via CURLOPT defaults,
// /root/reference/src/c++/library/http_client.cc; SslOptions
// grpc_client.h:42-58). The build image ships OpenSSL *runtime* libraries
// (libssl.so.3 / libcrypto.so.3) but no development headers, so this shim
// binds the dozen stable OpenSSL 3 entry points it needs at runtime with
// dlopen/dlsym. When the library is absent, Handshake fails with a clear
// error and cleartext operation is unaffected.
#pragma once

#include <cstddef>
#include <string>
#include <sys/types.h>

#include "tpuclient/error.h"

namespace tpuclient {

// Transport-level TLS settings, the union of what the two public option
// structs (SslOptions for gRPC, https defaults for HTTP) need.
struct TlsOptions {
  bool use_ssl = false;
  // PEM file paths (reference SslOptions semantics, grpc_client.h:46-57):
  // empty root file = OpenSSL default verify paths.
  std::string root_certificates;
  std::string private_key;
  std::string certificate_chain;
  bool verify_peer = true;  // verify the server certificate chain
  bool verify_host = true;  // match hostname against SAN/CN
  std::string alpn;         // ALPN protocol to offer ("h2" for gRPC)
  std::string server_name;  // SNI/verification override; empty = host
};

// One TLS session over an already-connected TCP socket (blocking IO).
class TlsSession {
 public:
  TlsSession() = default;
  ~TlsSession();
  TlsSession(const TlsSession&) = delete;
  TlsSession& operator=(const TlsSession&) = delete;

  // Whether libssl could be loaded on this machine.
  static bool Available();

  // Client handshake on fd. host is used for SNI and hostname verification
  // unless opts.server_name overrides it.
  Error Handshake(int fd, const std::string& host, const TlsOptions& opts);

  // recv/send-shaped IO. Return >0 bytes moved, 0 on clean TLS close,
  // kWantRead/kWantWrite when the socket is non-blocking and the operation
  // must be retried after the fd is readable/writable, or -1 on error with
  // *err filled. NOTE: one TlsSession must not be used from two threads at
  // once (OpenSSL SSL objects are not thread-safe) — callers with a reader
  // thread serialize access and use a non-blocking fd (see h2.cc).
  static constexpr ssize_t kWantRead = -2;
  static constexpr ssize_t kWantWrite = -3;
  ssize_t Read(void* buf, size_t n, Error* err);
  ssize_t Write(const void* buf, size_t n, Error* err);


  bool Active() const { return ssl_ != nullptr; }

  // Best-effort close_notify, then frees the session (keeps the fd open —
  // the socket owner closes it).
  void Close();

 private:
  void* ssl_ = nullptr;
  void* ctx_ = nullptr;
};

}  // namespace tpuclient
