// Shared API core of the C++ client library.
//
// Plays the role of the reference's common.{h,cc}
// (/root/reference/src/c++/library/common.h:26-617): request options, tensor
// descriptors with scatter-gather raw buffers, result interface, six-point
// request timers, and cumulative client-side statistics. The design is
// re-derived for this framework: tensors carry the v2 wire dtype string,
// data is referenced (not copied) until the transport needs it, and shared
// memory placement (system or TPU) replaces inline data per tensor.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tpuclient/error.h"

namespace tpuclient {

// v2-protocol dtype helpers (dtype table mirrors
// client_tpu/protocol/dtypes.py and reference perf_utils.h:114-121).
size_t DtypeByteSize(const std::string& datatype);  // 0 for BYTES/unknown

inline int64_t ElementCount(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (int64_t d : shape) {
    if (d < 0) return -1;
    n *= d;
  }
  return n;
}

// gRPC per-call message compression (reference grpc_client.h:323-382 takes
// grpc_compression_algorithm on Infer/AsyncInfer/stream; here the algorithm
// travels in InferOptions). GZIP/DEFLATE compress the framed request
// message (flag byte 1 + `grpc-encoding` header); compressed responses are
// inflated transparently.
enum class GrpcCompression { NONE, GZIP, DEFLATE };

// zlib helpers shared by the HTTP body compression and the gRPC message
// compression paths (internal).
namespace zutil {
Error Deflate(const std::string& in, bool gzip, std::string* out);
Error Inflate(const std::string& in, std::string* out);  // auto-detects
}  // namespace zutil

// Peer-supplied bytes never enter error/log text raw: non-printables are
// masked with '.' and the length capped, so a hostile server cannot plant
// terminal escapes or unbounded noise in client-side diagnostics.
std::string SanitizeForLog(const std::string& s, size_t cap = 64);

// Per-request options (reference InferOptions, common.h:156-208).
struct InferOptions {
  explicit InferOptions(const std::string& model_name_)
      : model_name(model_name_) {}

  std::string model_name;
  std::string model_version;
  std::string request_id;
  // Stateful-model sequence routing (reference common.h:173-198).
  uint64_t sequence_id = 0;
  bool sequence_start = false;
  bool sequence_end = false;
  uint64_t priority = 0;
  // Server-side queue timeout, microseconds (0 = none).
  uint64_t server_timeout_us = 0;
  // Client-side transport timeout, microseconds (0 = none).
  uint64_t client_timeout_us = 0;
  // Custom request parameters (v2 `parameters` object / InferParameter
  // map), e.g. {"max_tokens": 8} for generative models. Reserved protocol
  // keys (sequence_*, priority, timeout, binary_data_output) are set via
  // the typed fields above and must not be duplicated here.
  std::map<std::string, int64_t> int_parameters;
  std::map<std::string, std::string> string_parameters;
  std::map<std::string, bool> bool_parameters;
  // gRPC clients only: per-call message compression algorithm.
  GrpcCompression compression_algorithm = GrpcCompression::NONE;
};

// Input tensor: shape/dtype plus either scatter-gather host buffers or a
// shared-memory placement (reference InferInput, common.h:214-353).
class InferInput {
 public:
  static Error Create(InferInput** input, const std::string& name,
                      const std::vector<int64_t>& dims,
                      const std::string& datatype);

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  Error SetShape(const std::vector<int64_t>& dims);

  // Appends a no-copy reference to caller-owned memory; the caller keeps the
  // buffer alive until the request completes (scatter-gather bufs_,
  // reference common.h:337-339).
  Error AppendRaw(const uint8_t* data, size_t byte_size);
  Error AppendRaw(const std::vector<uint8_t>& data) {
    return AppendRaw(data.data(), data.size());
  }
  // BYTES tensors: appends one length-prefixed string element
  // (4-byte LE length + payload, reference common.cc AppendFromString).
  Error AppendFromString(const std::vector<std::string>& strings);

  Error SetSharedMemory(const std::string& region_name, size_t byte_size,
                        size_t offset = 0);
  Error Reset();

  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

  size_t TotalByteSize() const { return total_byte_size_; }
  const std::vector<std::pair<const uint8_t*, size_t>>& Buffers() const {
    return bufs_;
  }
  // Concatenate scatter-gather buffers (transport fast path iterates
  // Buffers() instead when it can stream).
  void CopyTo(std::string* out) const;

 private:
  InferInput(const std::string& name, const std::vector<int64_t>& dims,
             const std::string& datatype)
      : name_(name), shape_(dims), datatype_(datatype) {}

  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  std::vector<std::pair<const uint8_t*, size_t>> bufs_;
  // Backing store for AppendFromString (serialized BYTES payloads must
  // outlive the call site's temporaries).
  // deque: pointers into elements stay valid across later appends (bufs_
  // records (data,size) pairs into these strings; vector reallocation would
  // relocate SSO buffers and dangle them)
  std::deque<std::string> owned_;
  size_t total_byte_size_ = 0;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

// Requested output: by name, optionally class_count (classification
// extension) or shared-memory placement (reference InferRequestedOutput,
// common.h:359-431).
class InferRequestedOutput {
 public:
  static Error Create(InferRequestedOutput** output, const std::string& name,
                      size_t class_count = 0);

  const std::string& Name() const { return name_; }
  size_t ClassCount() const { return class_count_; }
  bool BinaryData() const { return binary_data_; }
  void SetBinaryData(bool b) { binary_data_ = b; }

  Error SetSharedMemory(const std::string& region_name, size_t byte_size,
                        size_t offset = 0);
  Error UnsetSharedMemory();
  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  InferRequestedOutput(const std::string& name, size_t class_count)
      : name_(name), class_count_(class_count) {}

  std::string name_;
  size_t class_count_;
  bool binary_data_ = true;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

// Result interface implemented per transport (reference InferResult,
// common.h:437-504).
class InferResult {
 public:
  virtual ~InferResult() = default;
  virtual Error ModelName(std::string* name) const = 0;
  virtual Error ModelVersion(std::string* version) const = 0;
  virtual Error Id(std::string* id) const = 0;
  virtual Error Shape(const std::string& output_name,
                      std::vector<int64_t>* shape) const = 0;
  virtual Error Datatype(const std::string& output_name,
                         std::string* datatype) const = 0;
  // Zero-copy view into the response buffer; valid while the result lives.
  virtual Error RawData(const std::string& output_name, const uint8_t** buf,
                        size_t* byte_size) const = 0;
  // BYTES tensor decode: splits the 4-byte-LE-length-prefixed stream
  // (reference StringData, common.h:474-480).
  virtual Error StringData(const std::string& output_name,
                           std::vector<std::string>* string_result) const;
  virtual Error RequestStatus() const = 0;
  virtual std::string DebugString() const = 0;
};

// Six-point per-request timestamps, nanoseconds
// (reference RequestTimers, common.h:509-589).
struct RequestTimers {
  enum class Kind {
    REQUEST_START,
    REQUEST_END,
    SEND_START,
    SEND_END,
    RECV_START,
    RECV_END
  };

  uint64_t request_start_ns = 0;
  uint64_t request_end_ns = 0;
  uint64_t send_start_ns = 0;
  uint64_t send_end_ns = 0;
  uint64_t recv_start_ns = 0;
  uint64_t recv_end_ns = 0;

  static uint64_t Now() {
    return std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Capture(Kind kind) {
    uint64_t now = Now();
    switch (kind) {
      case Kind::REQUEST_START:
        request_start_ns = now;
        break;
      case Kind::REQUEST_END:
        request_end_ns = now;
        break;
      case Kind::SEND_START:
        send_start_ns = now;
        break;
      case Kind::SEND_END:
        send_end_ns = now;
        break;
      case Kind::RECV_START:
        recv_start_ns = now;
        break;
      case Kind::RECV_END:
        recv_end_ns = now;
        break;
    }
  }
};

// Cumulative client-side statistics (reference InferStat, common.h:92-113).
// Splits a server URL into host + port: tolerates "scheme://" prefixes,
// bracketed IPv6 literals ("[::1]:8001"), bare IPv6 literals, and missing
// ports (default_port). Returns the scheme ("" when absent) so callers can
// derive TLS intent ("https"/"grpcs").
std::string SplitUrl(const std::string& url, int default_port,
                     std::string* host, int* port);

struct InferStat {
  size_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;
};

using OnCompleteFn = std::function<void(InferResult*)>;

// Client base: holds cumulative stats and the async worker machinery shared
// by transports (reference InferenceServerClient, common.h:118-151).
class InferenceServerClient {
 public:
  explicit InferenceServerClient(bool verbose)
      : verbose_(verbose), exiting_(false) {}
  virtual ~InferenceServerClient() = default;

  Error ClientInferStat(InferStat* infer_stat) const {
    std::lock_guard<std::mutex> lk(stat_mutex_);
    *infer_stat = infer_stat_;
    return Error::Success();
  }

 protected:
  void UpdateInferStat(const RequestTimers& timers) {
    std::lock_guard<std::mutex> lk(stat_mutex_);
    infer_stat_.completed_request_count++;
    infer_stat_.cumulative_total_request_time_ns +=
        timers.request_end_ns - timers.request_start_ns;
    infer_stat_.cumulative_send_time_ns +=
        timers.send_end_ns - timers.send_start_ns;
    infer_stat_.cumulative_receive_time_ns +=
        timers.recv_end_ns - timers.recv_start_ns;
  }

  bool verbose_;
  std::thread worker_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool exiting_;

 private:
  mutable std::mutex stat_mutex_;
  InferStat infer_stat_;
};

// BYTES tensor codec helpers (4-byte LE length prefix per element,
// reference utils/__init__.py:187-271 and perf_utils.h:122-129).
void SerializeStringTensor(const std::vector<std::string>& strings,
                           std::string* out);
Error DeserializeStringTensor(const uint8_t* buf, size_t byte_size,
                              std::vector<std::string>* out);

}  // namespace tpuclient
