// POSIX shared-memory helpers for the system-shm data plane.
//
// Same surface as the reference's shm_utils.{h,cc}
// (/root/reference/src/c++/library/shm_utils.cc:38-106): create/map/unmap/
// unlink a /dev/shm segment that the server maps by key after a
// RegisterSystemSharedMemory control call.
#pragma once

#include <cstddef>

#include "tpuclient/error.h"

namespace tpuclient {

// shm_open(O_CREAT|O_RDWR) + ftruncate; returns the fd.
Error CreateSharedMemoryRegion(const std::string& shm_key, size_t byte_size,
                               int* shm_fd);

// mmap(PROT_READ|PROT_WRITE, MAP_SHARED) at `offset`.
Error MapSharedMemory(int shm_fd, size_t offset, size_t byte_size,
                      void** shm_addr);

Error CloseSharedMemory(int shm_fd);
Error UnlinkSharedMemoryRegion(const std::string& shm_key);
Error UnmapSharedMemory(void* shm_addr, size_t byte_size);

}  // namespace tpuclient
