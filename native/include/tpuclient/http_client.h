// HTTP/REST client for the KServe v2 protocol.
//
// Covers the surface of the reference's InferenceServerHttpClient
// (/root/reference/src/c++/library/http_client.h:62-461): sync Infer, async
// Infer with completion callbacks, and the full control plane (live/ready/
// metadata/config/repository index/load/unload/statistics/shared-memory
// register-unregister-status). The transport is re-designed for this
// framework: a dependency-free HTTP/1.1 keep-alive connection pool over
// POSIX sockets with writev scatter-gather request bodies (no libcurl in the
// image; the reference streams its scatter-gather deque through curl's
// READFUNCTION, http_client.cc:1370-1385 — writev achieves the same
// zero-concat send). Binary tensor framing follows the v2 binary extension:
// JSON head + concatenated binary tails addressed by the
// Inference-Header-Content-Length header (http_client.cc:1396-1407).
#pragma once

#include <atomic>
#include <deque>
#include <memory>
#include <queue>

#include "tpuclient/common.h"
#include "tpuclient/json.h"
#include "tpuclient/tls.h"

namespace tpuclient {

using Headers = std::map<std::string, std::string>;
using Parameters = std::map<std::string, std::string>;

// TLS settings for https:// endpoints. The reference client gets these
// semantics from libcurl (CURLOPT_SSL_VERIFYPEER/-HOST, CURLOPT_CAINFO,
// CURLOPT_SSLCERT/-KEY); exposed here explicitly.
struct HttpSslOptions {
  bool verify_peer = true;  // verify the server certificate chain
  bool verify_host = true;  // match hostname against the certificate
  std::string ca_info;      // CA bundle PEM path; empty = system defaults
  std::string cert;         // client certificate PEM path
  std::string key;          // client private key PEM path
};

// One pooled HTTP/1.1 keep-alive connection.
class HttpConnection;

class InferResultHttp : public InferResult {
 public:
  // Parses the response: JSON head (sized by Inference-Header-Content-Length
  // or the whole body), then maps each binary output by walking offsets in
  // order (reference InferResultHttp, http_client.cc:752-835).
  static Error Create(InferResult** result, std::string&& response_body,
                      size_t header_length, int http_status);

  Error ModelName(std::string* name) const override;
  Error ModelVersion(std::string* version) const override;
  Error Id(std::string* id) const override;
  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override;
  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override;
  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override;
  Error RequestStatus() const override;
  std::string DebugString() const override;

  const JsonPtr& Head() const { return head_; }

 private:
  InferResultHttp() = default;
  std::string body_;
  JsonPtr head_;
  Error status_;
  struct OutputRef {
    JsonPtr meta;
    const uint8_t* data = nullptr;  // into body_ or json_backing
    size_t byte_size = 0;
    // Packed bytes materialized from a JSON data array (non-binary output).
    std::shared_ptr<std::string> json_backing;
  };
  std::map<std::string, OutputRef> outputs_;
};

class InferenceServerHttpClient : public InferenceServerClient {
 public:
  // Request/response body compression (reference CompressionType +
  // CompressData, http_client.cc:122-198; zlib deflate and gzip framings).
  enum class CompressionType { NONE, DEFLATE, GZIP };

  // server_url: "host:port", "http://host:port" or "https://host:port"
  // (https implies TLS using ssl_options).
  static Error Create(std::unique_ptr<InferenceServerHttpClient>* client,
                      const std::string& server_url, bool verbose = false,
                      const HttpSslOptions& ssl_options = HttpSslOptions());
  ~InferenceServerHttpClient() override;

  // -- control plane (reference http_client.h:112-341) ---------------------
  Error IsServerLive(bool* live, const Headers& headers = {});
  Error IsServerReady(bool* ready, const Headers& headers = {});
  Error IsModelReady(bool* ready, const std::string& model_name,
                     const std::string& model_version = "",
                     const Headers& headers = {});
  Error ServerMetadata(JsonPtr* metadata, const Headers& headers = {});
  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string& model_version = "",
                      const Headers& headers = {});
  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string& model_version = "",
                    const Headers& headers = {});
  Error ModelRepositoryIndex(JsonPtr* index, const Headers& headers = {});
  Error LoadModel(const std::string& model_name, const Headers& headers = {},
                  const std::string& config = "");
  Error UnloadModel(const std::string& model_name,
                    const Headers& headers = {});
  Error ModelInferenceStatistics(JsonPtr* infer_stat,
                                 const std::string& model_name = "",
                                 const std::string& model_version = "",
                                 const Headers& headers = {});

  // -- shared memory control (reference http_client.h:239-341) -------------
  Error SystemSharedMemoryStatus(JsonPtr* status,
                                 const std::string& region_name = "",
                                 const Headers& headers = {});
  Error RegisterSystemSharedMemory(const std::string& name,
                                   const std::string& key, size_t byte_size,
                                   size_t offset = 0,
                                   const Headers& headers = {});
  Error UnregisterSystemSharedMemory(const std::string& name = "",
                                     const Headers& headers = {});
  Error TpuSharedMemoryStatus(JsonPtr* status,
                              const std::string& region_name = "",
                              const Headers& headers = {});
  // raw_handle: opaque device-region handle bytes (base64-encoded on the
  // wire, as the reference encodes cudaIpcMemHandle_t for HTTP transport).
  Error RegisterTpuSharedMemory(const std::string& name,
                                const std::string& raw_handle,
                                size_t byte_size, int device_id = 0,
                                const Headers& headers = {});
  Error UnregisterTpuSharedMemory(const std::string& name = "",
                                  const Headers& headers = {});

  // -- inference -----------------------------------------------------------
  // request_compression_algorithm: deflate/gzip-compress the request body
  // (Content-Encoding); response_compression_algorithm: advertise
  // Accept-Encoding and transparently decompress the response (reference
  // http_client.h:354-373).
  Error Infer(InferResult** result, const InferOptions& options,
              const std::vector<InferInput*>& inputs,
              const std::vector<const InferRequestedOutput*>& outputs = {},
              const Headers& headers = {},
              CompressionType request_compression_algorithm =
                  CompressionType::NONE,
              CompressionType response_compression_algorithm =
                  CompressionType::NONE);

  // Async: request is sent on a worker connection; callback fires from the
  // worker thread (reference AsyncInfer + AsyncTransfer curl-multi loop,
  // http_client.cc:1303-1368, 1574-1641 — here a pool of keep-alive worker
  // connections, one in-flight request each).
  Error AsyncInfer(OnCompleteFn callback, const InferOptions& options,
                   const std::vector<InferInput*>& inputs,
                   const std::vector<const InferRequestedOutput*>& outputs = {},
                   const Headers& headers = {},
                   CompressionType request_compression_algorithm =
                       CompressionType::NONE,
                   CompressionType response_compression_algorithm =
                       CompressionType::NONE);

  // Sizes the async worker-connection pool (one in-flight request per
  // worker). Takes effect for workers not yet spawned; call before the
  // first AsyncInfer for full effect.
  void SetMaxAsyncWorkers(size_t n) {
    if (n > 0) max_async_workers_ = n;
  }

  // Raw entry points used by the generate/profile tooling.
  Error Get(const std::string& path, JsonPtr* response,
            const Headers& headers = {});
  Error Post(const std::string& path, const std::string& body,
             JsonPtr* response, const Headers& headers = {});

 private:
  InferenceServerHttpClient(const std::string& host, int port, bool verbose,
                            const TlsOptions& tls);

  struct PreparedRequest {
    std::string path;
    std::string json_head;
    size_t header_length = 0;
    // scatter-gather segments after the head (input raw buffers)
    std::vector<std::pair<const uint8_t*, size_t>> tail;
    size_t total_body = 0;
    uint64_t timeout_us = 0;
    // Compression: when content_encoding is set, `compressed` replaces
    // head+tail as the single body segment (header_length still names the
    // uncompressed JSON head size for the server's split).
    std::string compressed;
    std::string content_encoding;
    std::string accept_encoding;
  };

  // Collapses prep's head+tail into one deflate/gzip body (reference
  // CompressData, http_client.cc:122-198).
  static Error CompressRequest(PreparedRequest* prep, CompressionType type);

  Error PrepareInferRequest(
      PreparedRequest* prep, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);

  Error DoInfer(HttpConnection* conn, const PreparedRequest& prep,
                const Headers& headers, RequestTimers* timers,
                InferResult** result);

  // Connection pool keyed by nothing (single endpoint); borrowed per call.
  std::unique_ptr<HttpConnection> BorrowConnection();
  void ReturnConnection(std::unique_ptr<HttpConnection> conn);

  struct AsyncJob {
    PreparedRequest prep;
    Headers headers;
    OnCompleteFn callback;
    // Keep-alive copies: async callers' input buffers must survive until
    // the worker sends them, so raw segments are copied into `body_copy`
    // at enqueue (the reference instead requires callers to keep inputs
    // alive; copying here removes that footgun at ~1 memcpy cost).
    std::string body_copy;
  };

  void AsyncWorkerLoop();

  std::string host_;
  int port_;
  TlsOptions tls_;

  std::mutex pool_mutex_;
  std::deque<std::unique_ptr<HttpConnection>> pool_;

  std::mutex async_mutex_;
  std::condition_variable async_cv_;
  std::queue<std::unique_ptr<AsyncJob>> async_queue_;
  std::vector<std::thread> async_workers_;
  std::atomic<bool> async_exit_{false};
  size_t max_async_workers_ = 8;
};

}  // namespace tpuclient
