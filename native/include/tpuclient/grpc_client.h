// gRPC client for the v2 inference protocol: sync, async, and bidirectional
// streaming infer plus the full control plane, speaking standard gRPC over
// cleartext HTTP/2 so it interoperates with any v2 gRPC server (including
// this framework's grpcio-based server and upstream Triton).
//
// Plays the role of the reference's grpc_client.{h,cc}
// (/root/reference/src/c++/library/grpc_client.h:99, grpc_client.cc), with
// the same surface: process-global channel cache keyed by URL
// (grpc_client.cc:48-123), request-proto reuse across calls
// (grpc_client.cc:1113-1210), zero-parse results over protobuf
// (grpc_client.cc:144-365), async completion dispatch (reference uses a
// CompletionQueue drain thread, grpc_client.cc:1225-1268 — here a ready-
// queue fed by the HTTP/2 reader), and a single bidi stream with a reader
// thread for streaming infer (grpc_client.cc:986-1080). The transport
// itself is the in-tree dependency-free HTTP/2 stack (src/h2.h); messages
// are protoc-generated C++ from protocol/protos/grpc_service.proto.
#pragma once

#include <atomic>
#include <climits>
#include <deque>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "grpc_service.pb.h"
#include "tpuclient/common.h"
#include "tpuclient/error.h"

namespace tpuclient {

namespace h2 {
class Connection;
}

using GrpcHeaders = std::map<std::string, std::string>;

// Generic unary gRPC call over an established h2 connection: frames the
// request message, drives one stream to half-close, parses the single
// response message, maps grpc-status. Lets auxiliary gRPC service clients
// (the perf harness's TENSORFLOW_SERVING kind speaking
// /tensorflow.serving.PredictionService/*) reuse the in-tree transport.
Error GrpcUnaryCall(h2::Connection* conn, const std::string& authority,
                    const std::string& method_path,
                    const google::protobuf::Message& request,
                    google::protobuf::Message* response,
                    uint64_t timeout_us = 0,
                    const GrpcHeaders& headers = {});

// TLS settings for encrypted channels (reference SslOptions,
// grpc_client.h:42-58): PEM file paths; empty root_certificates = system
// default verify paths.
struct SslOptions {
  std::string root_certificates;  // server root CA bundle (PEM file)
  std::string private_key;        // client private key (PEM file)
  std::string certificate_chain;  // client certificate chain (PEM file)
};

// Transport keepalive (reference KeepAliveOptions, grpc_client.h:61-81,
// semantics per gRPC core's keepalive doc): defaults disable pinging.
struct KeepAliveOptions {
  int keepalive_time_ms = INT_MAX;     // ping period; INT_MAX = off
  int keepalive_timeout_ms = 20000;    // wait for ack before failing
  bool keepalive_permit_without_calls = false;
  int http2_max_pings_without_data = 2;  // 0 = unlimited
};

// Result wrapper over the response protobuf: output lookups index straight
// into raw_output_contents with no copies (reference InferResultGrpc,
// grpc_client.cc:144-365).
class InferResultGrpc : public InferResult {
 public:
  static Error Create(InferResult** result,
                      std::shared_ptr<inference::ModelInferResponse> response,
                      Error status = Error::Success());

  Error ModelName(std::string* name) const override;
  Error ModelVersion(std::string* version) const override;
  Error Id(std::string* id) const override;
  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override;
  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override;
  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override;
  Error RequestStatus() const override;
  std::string DebugString() const override;

  const inference::ModelInferResponse& Response() const { return *response_; }

 private:
  InferResultGrpc(std::shared_ptr<inference::ModelInferResponse> response,
                  Error status);
  std::shared_ptr<inference::ModelInferResponse> response_;
  Error status_;
  // output name -> index into response outputs
  std::map<std::string, int> index_;
  // output name -> index into raw_output_contents (-1 = shared memory; the
  // wire carries no raw entry for shm outputs)
  std::map<std::string, int> raw_index_;
};

class InferenceServerGrpcClient : public InferenceServerClient {
 public:
  // url: "host:port" (an "http://"/"grpc://" prefix is tolerated and
  // stripped; "https://"/"grpcs://" implies use_ssl).
  // use_cached_channel: reuse one HTTP/2 connection per URL process-wide
  // (reference grpc_client.cc:48-123 channel cache; TLS and cleartext
  // channels cache under distinct keys).
  // use_ssl + ssl_options: TLS with ALPN "h2" (reference
  // grpc_client.h:108-118). keepalive_options: transport PING keepalive.
  static Error Create(std::unique_ptr<InferenceServerGrpcClient>* client,
                      const std::string& url, bool verbose = false,
                      bool use_cached_channel = true, bool use_ssl = false,
                      const SslOptions& ssl_options = SslOptions(),
                      const KeepAliveOptions& keepalive_options =
                          KeepAliveOptions());
  ~InferenceServerGrpcClient() override;

  // -- control plane (reference grpc_client.h:125-312) --
  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(bool* ready, const std::string& model_name,
                     const std::string& model_version = "");
  Error ServerMetadata(inference::ServerMetadataResponse* response);
  Error ModelMetadata(inference::ModelMetadataResponse* response,
                      const std::string& model_name,
                      const std::string& model_version = "");
  Error ModelConfig(inference::ModelConfigResponse* response,
                    const std::string& model_name,
                    const std::string& model_version = "");
  Error ModelRepositoryIndex(inference::RepositoryIndexResponse* response);
  Error LoadModel(const std::string& model_name);
  Error UnloadModel(const std::string& model_name);
  Error ModelInferenceStatistics(inference::ModelStatisticsResponse* response,
                                 const std::string& model_name = "",
                                 const std::string& model_version = "");

  // -- shared-memory control (system + TPU; reference grpc_client.h:232-312,
  //    TPU replacing CUDA per SURVEY.md §5.8) --
  Error RegisterSystemSharedMemory(const std::string& name,
                                   const std::string& key, size_t byte_size,
                                   size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  Error SystemSharedMemoryStatus(
      inference::SystemSharedMemoryStatusResponse* response);
  Error RegisterTpuSharedMemory(const std::string& name,
                                const std::string& raw_handle,
                                int64_t device_id, size_t byte_size);
  Error UnregisterTpuSharedMemory(const std::string& name = "");
  Error TpuSharedMemoryStatus(
      inference::TpuSharedMemoryStatusResponse* response);

  // -- data plane --
  Error Infer(InferResult** result, const InferOptions& options,
              const std::vector<InferInput*>& inputs,
              const std::vector<const InferRequestedOutput*>& outputs = {},
              const GrpcHeaders& headers = {});
  Error AsyncInfer(OnCompleteFn callback, const InferOptions& options,
                   const std::vector<InferInput*>& inputs,
                   const std::vector<const InferRequestedOutput*>& outputs = {},
                   const GrpcHeaders& headers = {});

  // Bidirectional streaming: one ModelStreamInfer stream per client.
  // callback fires once per stream response, in stream order.
  // `compression` declares the stream's grpc-encoding up front; subsequent
  // AsyncStreamInfer calls whose options request that algorithm send
  // compressed messages (reference grpc_client.h:364-382).
  Error StartStream(OnCompleteFn callback, const GrpcHeaders& headers = {},
                    GrpcCompression compression = GrpcCompression::NONE);
  Error AsyncStreamInfer(const InferOptions& options,
                         const std::vector<InferInput*>& inputs,
                         const std::vector<const InferRequestedOutput*>&
                             outputs = {});
  Error StopStream();

 private:
  explicit InferenceServerGrpcClient(bool verbose);

  Error Connect(const std::string& url, bool use_cached_channel,
                bool use_ssl, const SslOptions& ssl_options,
                const KeepAliveOptions& keepalive_options);
  // Unary gRPC call: serialize request, open stream, send, await trailers.
  Error Rpc(const std::string& method,
            const google::protobuf::Message& request,
            google::protobuf::Message* response, uint64_t timeout_us = 0,
            const GrpcHeaders& headers = {});
  // Builds request headers / parses "grpc-status" trailers.
  void BuildRequest(const InferOptions& options,
                    const std::vector<InferInput*>& inputs,
                    const std::vector<const InferRequestedOutput*>& outputs,
                    inference::ModelInferRequest* request);

  struct AsyncJob {
    int32_t sid = 0;
    OnCompleteFn callback;
    RequestTimers timers;
  };
  void AsyncWorker();
  void StreamWorker();

  std::shared_ptr<h2::Connection> conn_;
  GrpcCompression stream_compression_ = GrpcCompression::NONE;
  std::string authority_;

  // Sync-path request proto, reused across calls (reference infer_request_
  // member, grpc_client.h:433).
  inference::ModelInferRequest sync_request_;
  std::mutex sync_mutex_;

  // Async completion machinery: the h2 reader signals readiness; the worker
  // thread inspects streams and fires user callbacks outside all locks.
  std::mutex async_mutex_;
  std::condition_variable async_cv_;
  std::deque<std::shared_ptr<AsyncJob>> async_jobs_;
  std::thread async_worker_;
  std::atomic<bool> async_exit_{false};
  // Bumped by the h2 reader's on_event and by job submission; the worker
  // sleeps until it changes (with a timed backstop for the unlocked notify).
  std::atomic<uint64_t> async_events_{0};

  // Streaming state.
  std::mutex stream_mutex_;
  // Serializes whole gRPC messages onto the bidi stream: h2 SendData locks
  // per DATA chunk, so without this two AsyncStreamInfer calls (or a racing
  // StopStream half-close) could interleave chunks of different messages.
  std::mutex stream_send_mutex_;
  int32_t stream_sid_ = 0;
  bool stream_active_ = false;
  OnCompleteFn stream_callback_;
  std::thread stream_worker_;
  std::atomic<bool> stream_exit_{false};
};

}  // namespace tpuclient
