// Minimal self-contained JSON DOM (parse + serialize).
//
// The reference links rapidjson / TritonJson for its request building and
// response parsing (/root/reference/src/c++/library/http_client.cc:301-434,
// json_utils.h:35); neither is available in this image, so the framework
// carries its own ~400-line DOM sized for the v2 protocol: numbers kept as
// int64/uint64/double, strings, bools, arrays, objects (insertion-ordered).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tpuclient/error.h"

namespace tpuclient {

class Json;
using JsonPtr = std::shared_ptr<Json>;

class Json {
 public:
  enum class Type { kNull, kBool, kInt, kUint, kDouble, kString, kArray, kObject };

  Json() : type_(Type::kNull) {}

  static JsonPtr MakeNull() { return std::make_shared<Json>(); }
  static JsonPtr MakeBool(bool v);
  static JsonPtr MakeInt(int64_t v);
  static JsonPtr MakeUint(uint64_t v);
  static JsonPtr MakeDouble(double v);
  static JsonPtr MakeString(std::string v);
  static JsonPtr MakeArray();
  static JsonPtr MakeObject();

  Type type() const { return type_; }
  bool IsNull() const { return type_ == Type::kNull; }
  bool IsBool() const { return type_ == Type::kBool; }
  bool IsNumber() const {
    return type_ == Type::kInt || type_ == Type::kUint || type_ == Type::kDouble;
  }
  bool IsString() const { return type_ == Type::kString; }
  bool IsArray() const { return type_ == Type::kArray; }
  bool IsObject() const { return type_ == Type::kObject; }

  bool AsBool() const { return bool_; }
  int64_t AsInt() const;
  uint64_t AsUint() const;
  double AsDouble() const;
  const std::string& AsString() const { return str_; }

  // Array access
  size_t Size() const { return arr_.size(); }
  const JsonPtr& At(size_t i) const { return arr_[i]; }
  void Append(JsonPtr v) { arr_.push_back(std::move(v)); }

  // Object access (insertion order preserved for serialization)
  JsonPtr Get(const std::string& key) const;
  bool Has(const std::string& key) const;
  void Set(const std::string& key, JsonPtr v);
  const std::vector<std::pair<std::string, JsonPtr>>& Members() const {
    return obj_;
  }

  // Convenience setters
  void Set(const std::string& key, const std::string& v) {
    Set(key, MakeString(v));
  }
  void Set(const std::string& key, const char* v) { Set(key, MakeString(v)); }
  void Set(const std::string& key, int64_t v) { Set(key, MakeInt(v)); }
  void Set(const std::string& key, uint64_t v) { Set(key, MakeUint(v)); }
  void Set(const std::string& key, int v) { Set(key, MakeInt(v)); }
  void Set(const std::string& key, bool v) { Set(key, MakeBool(v)); }
  void Set(const std::string& key, double v) { Set(key, MakeDouble(v)); }

  std::string Serialize() const;
  void SerializeTo(std::string* out) const;

  // Parses `text` (full buffer must be one JSON value + optional whitespace).
  static Error Parse(const char* text, size_t len, JsonPtr* out);
  static Error Parse(const std::string& text, JsonPtr* out) {
    return Parse(text.data(), text.size(), out);
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  uint64_t uint_ = 0;
  double dbl_ = 0.0;
  std::string str_;
  std::vector<JsonPtr> arr_;
  std::vector<std::pair<std::string, JsonPtr>> obj_;
};

}  // namespace tpuclient
