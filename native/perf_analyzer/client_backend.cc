#include "client_backend.h"

#include "tpuclient/http_client.h"

using tpuclient::Error;
using tpuclient::JsonPtr;

namespace tpuperf {

Error ClientBackend::RegisterSystemSharedMemory(const std::string&,
                                                const std::string&, size_t) {
  return Error("shared memory not supported by this backend", 400);
}

Error ClientBackend::UnregisterSystemSharedMemory(const std::string&) {
  return Error("shared memory not supported by this backend", 400);
}

Error ClientBackend::RegisterTpuSharedMemory(const std::string&,
                                             const std::string&, int64_t,
                                             size_t) {
  return Error("tpu shared memory not supported by this backend", 400);
}

Error ClientBackend::UnregisterTpuSharedMemory(const std::string&) {
  return Error("tpu shared memory not supported by this backend", 400);
}

namespace {

class HttpClientBackend : public ClientBackend {
 public:
  static Error Create(const std::string& url, bool verbose,
                      size_t max_async_concurrency,
                      std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<HttpClientBackend>(new HttpClientBackend());
    Error err =
        tpuclient::InferenceServerHttpClient::Create(&b->client_, url, verbose);
    if (!err.IsOk()) return err;
    b->client_->SetMaxAsyncWorkers(max_async_concurrency);
    *backend = std::move(b);
    return Error::Success();
  }

  Error ServerExtensions(std::vector<std::string>* extensions) override {
    JsonPtr md;
    Error err = client_->ServerMetadata(&md);
    if (!err.IsOk()) return err;
    extensions->clear();
    JsonPtr ext = md->Get("extensions");
    if (ext && ext->IsArray()) {
      for (size_t i = 0; i < ext->Size(); ++i) {
        if (ext->At(i)->IsString()) extensions->push_back(ext->At(i)->AsString());
      }
    }
    return Error::Success();
  }

  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string& version) override {
    return client_->ModelMetadata(metadata, model_name, version);
  }

  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string& version) override {
    return client_->ModelConfig(config, model_name, version);
  }

  Error Infer(tpuclient::InferResult** result,
              const tpuclient::InferOptions& options,
              const std::vector<tpuclient::InferInput*>& inputs,
              const std::vector<const tpuclient::InferRequestedOutput*>&
                  outputs) override {
    return client_->Infer(result, options, inputs, outputs);
  }

  Error AsyncInfer(tpuclient::OnCompleteFn callback,
                   const tpuclient::InferOptions& options,
                   const std::vector<tpuclient::InferInput*>& inputs,
                   const std::vector<const tpuclient::InferRequestedOutput*>&
                       outputs) override {
    return client_->AsyncInfer(std::move(callback), options, inputs, outputs);
  }

  Error ModelInferenceStatistics(std::map<std::string, ModelStatistics>* stats,
                                 const std::string& model_name) override {
    JsonPtr body;
    Error err = client_->ModelInferenceStatistics(&body, model_name);
    if (!err.IsOk()) return err;
    return ParseModelStatsJson(body, stats);
  }

  Error ClientInferStat(tpuclient::InferStat* stat) override {
    return client_->ClientInferStat(stat);
  }

  Error RegisterSystemSharedMemory(const std::string& name,
                                   const std::string& key,
                                   size_t byte_size) override {
    return client_->RegisterSystemSharedMemory(name, key, byte_size);
  }

  Error UnregisterSystemSharedMemory(const std::string& name) override {
    return client_->UnregisterSystemSharedMemory(name);
  }

  Error RegisterTpuSharedMemory(const std::string& name,
                                const std::string& raw_handle,
                                int64_t device_id,
                                size_t byte_size) override {
    return client_->RegisterTpuSharedMemory(name, raw_handle, byte_size,
                                            static_cast<int>(device_id));
  }

  Error UnregisterTpuSharedMemory(const std::string& name) override {
    return client_->UnregisterTpuSharedMemory(name);
  }

 private:
  HttpClientBackend() = default;
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client_;
};

}  // namespace

Error ParseModelStatsJson(const JsonPtr& body,
                          std::map<std::string, ModelStatistics>* stats) {
  stats->clear();
  JsonPtr list = body->Get("model_stats");
  if (!list || !list->IsArray())
    return Error("statistics response missing model_stats", 400);
  for (size_t i = 0; i < list->Size(); ++i) {
    JsonPtr m = list->At(i);
    if (!m->IsObject()) continue;
    JsonPtr name = m->Get("name");
    if (!name || !name->IsString()) continue;
    ModelStatistics ms;
    auto u64 = [&](const JsonPtr& obj, const char* key) -> uint64_t {
      if (!obj) return 0;
      JsonPtr v = obj->Get(key);
      return v && v->IsNumber() ? v->AsUint() : 0;
    };
    ms.inference_count = u64(m, "inference_count");
    ms.execution_count = u64(m, "execution_count");
    JsonPtr infer_stats = m->Get("inference_stats");
    if (infer_stats && infer_stats->IsObject()) {
      auto phase = [&](const char* key, uint64_t* count_out) -> uint64_t {
        JsonPtr p = infer_stats->Get(key);
        if (!p || !p->IsObject()) return 0;
        if (count_out) *count_out = u64(p, "count");
        return u64(p, "ns");
      };
      uint64_t success_count = 0;
      ms.cumulative_request_time_ns = phase("success", &success_count);
      ms.success_count = success_count;
      ms.queue_time_ns = phase("queue", nullptr);
      ms.compute_input_time_ns = phase("compute_input", nullptr);
      ms.compute_infer_time_ns = phase("compute_infer", nullptr);
      ms.compute_output_time_ns = phase("compute_output", nullptr);
    }
    (*stats)[name->AsString()] = ms;
  }
  return Error::Success();
}

Error ClientBackendFactory::Create(
    std::unique_ptr<ClientBackend>* backend) const {
  switch (kind_) {
    case BackendKind::TPU_HTTP:
      return HttpClientBackend::Create(url_, verbose_, max_async_concurrency_,
                                       backend);
    case BackendKind::TPU_GRPC:
      return CreateGrpcBackend(url_, verbose_, backend);
    case BackendKind::TPU_CAPI:
      return CreateCApiBackend(capi_lib_path_, capi_models_, capi_repo_root_,
                               backend);
    case BackendKind::TENSORFLOW_SERVING:
      return CreateTfServeBackend(url_, verbose_, backend);
    case BackendKind::TORCHSERVE:
      return CreateTorchServeBackend(url_, verbose_, backend);
  }
  return Error("unknown backend kind", 400);
}

}  // namespace tpuperf
