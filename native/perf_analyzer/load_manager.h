// Base load-generation machinery.
//
// Counterpart of the reference's load_manager.{h,cc}
// (/root/reference/src/c++/perf_analyzer/load_manager.h:73-248, load_manager
// .cc:219-721): prepares request tensors from the DataLoader, optionally
// stages them in registered shared-memory regions, tracks per-worker-thread
// request timestamp vectors, and handles stateful-model sequence bookkeeping
// (sequence_id / start / end flags, one live sequence per context —
// concurrency_manager.cc:148-152).
#pragma once

#include <atomic>
#include <condition_variable>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "client_backend.h"
#include "data_loader.h"
#include "model_parser.h"
#include "perf_utils.h"

namespace tpuperf {

struct LoadOptions {
  int32_t batch_size = 1;
  bool async = false;
  // Drive requests over one bidi gRPC stream per worker instead of unary
  // calls (reference --streaming, main.cc:610-748; sequence models keep
  // per-context ordering because each context's requests are serialized).
  bool streaming = false;
  size_t max_threads = 16;
  SharedMemoryType shm_type = SharedMemoryType::NONE;
  size_t output_shm_size = 100 * 1024;
  // sequence load (reference load_manager.cc:676-719)
  uint64_t start_sequence_id = 1;
  uint64_t sequence_length = 20;
  // Distinct concurrent sequences under request-rate/custom load
  // (reference --num-of-sequences, default 4; concurrency mode sizes the
  // sequence pool by the concurrency level instead).
  size_t num_of_sequences = 4;
  // Per-call gRPC message compression for every generated request
  // (reference --grpc-compression-algorithm).
  tpuclient::GrpcCompression compression = tpuclient::GrpcCompression::NONE;
  uint64_t request_timeout_us = 0;
};

class LoadManager {
 public:
  virtual ~LoadManager();

  // Worker liveness check (reference CheckHealth, load_manager.cc:131).
  tpuclient::Error CheckHealth();

  // Hands collected request records to the profiler and resets the
  // accumulators (reference SwapTimestamps).
  tpuclient::Error SwapTimestamps(TimestampVector* out);
  size_t CountCollectedRequests();

  // Sum of per-backend cumulative client stats (send/recv times).
  tpuclient::Error GetAccumulatedClientStat(tpuclient::InferStat* stat);

  int32_t BatchSize() const { return options_.batch_size; }

  // Sends n unmeasured synchronous inferences on a dedicated backend so
  // first-request server-side compilation (XLA warms one executable per
  // batch bucket) never lands inside a measurement window. Reference
  // perf_analyzer relies on stability-window rejection instead; explicit
  // warmup converges far faster when compile takes tens of seconds.
  tpuclient::Error WarmUp(size_t n);

 protected:
  LoadManager(const LoadOptions& options, ClientBackendFactory factory,
              std::shared_ptr<ModelParser> parser,
              std::shared_ptr<DataLoader> data_loader);

  // One worker thread's accumulators; guarded by its mutex.
  struct ThreadStat {
    std::mutex mu;
    TimestampVector requests;
    tpuclient::Error status;
  };

  // One outstanding-request slot: tensors + options, reused across requests
  // (the reference reuses request objects for allocation hygiene, §5.9).
  struct InferContext {
    std::vector<tpuclient::InferInput*> inputs;
    std::vector<const tpuclient::InferRequestedOutput*> outputs;
    std::unique_ptr<tpuclient::InferOptions> options;
    size_t stream = 0;
    size_t step = 0;
    // sequence state (valid when is_sequence_)
    uint64_t seq_id = 0;
    uint64_t seq_remaining = 0;
    // Written by transport callback threads, scanned by the worker thread:
    // release/acquire so the worker's free-context scan observes the
    // callback's timestamp recording before reusing the context.
    std::atomic<bool> inflight{false};
    uint64_t start_ns = 0;
  };

  // One dispatched-but-unanswered streaming request (keyed by request id:
  // the bidi stream multiplexes every context's responses onto one
  // callback).
  struct StreamPending {
    InferContext* ctx = nullptr;
    uint64_t start_ns = 0;
    bool seq_end = false;
  };

  struct ThreadConfig {
    size_t index = 0;
    // Context-pool cap for this worker: bounds the number of distinct
    // live sequences it drives (set from LoadOptions.num_of_sequences by
    // the rate manager for sequence models; unbounded otherwise).
    size_t max_ctxs = SIZE_MAX;
    // Written by StartWorkers while a previously-started worker may still be
    // mid-iteration (PauseWorkers does not quiesce), read in the schedule
    // walk — atomic to keep that benign overlap defined.
    std::atomic<size_t> stride{1};
    std::unique_ptr<ClientBackend> backend;
    std::vector<std::unique_ptr<InferContext>> ctxs;
    // streaming mode state (one stream per worker/backend)
    bool stream_started = false;
    std::mutex stream_mu;
    std::map<std::string, StreamPending> stream_pending;
    std::atomic<uint64_t> stream_seq{0};
  };

  // Registered shm staging for one input data chunk.
  struct ShmRegion {
    std::string name;
    std::string key;
    void* base = nullptr;
    size_t byte_size = 0;
    int fd = -1;
  };

  tpuclient::Error MakeContext(ThreadConfig* config, InferContext** out);
  // Points ctx inputs at the (stream, step) data (or its shm region) and
  // sets sequence options when the model is sequence-batched.
  tpuclient::Error PrepareRequest(InferContext* ctx);
  void RecordRequest(ThreadStat* stat, uint64_t start_ns, uint64_t end_ns,
                     bool sequence_end, bool delayed);
  void StopWorkerThreads();

  // shm staging (reference InitSharedMemory, load_manager.cc:256-446)
  tpuclient::Error InitSharedMemory(ClientBackend* backend);
  void CleanupSharedMemory(ClientBackend* backend);
  tpuclient::Error RegisterShmRegion(ClientBackend* backend,
                                     const ShmRegion& region);
  static std::string MakeTpuHandle(const std::string& key, size_t byte_size,
                                   int device_id);
  std::string ShmRegionName(const std::string& input, size_t stream,
                            size_t step) const;

  LoadOptions options_;
  ClientBackendFactory factory_;
  std::shared_ptr<ModelParser> parser_;
  std::shared_ptr<DataLoader> data_loader_;
  bool is_sequence_ = false;

  std::vector<std::shared_ptr<ThreadStat>> thread_stats_;
  std::vector<std::shared_ptr<ThreadConfig>> thread_configs_;
  // WarmUp's dedicated backend/context — kept for the manager's lifetime so
  // the destructor's shm cleanup and tensor frees cover it (the warmup shm
  // registrations outlive WarmUp by design: workers reuse them).
  std::shared_ptr<ThreadConfig> warmup_config_;
  std::vector<std::thread> threads_;
  std::atomic<bool> exit_{false};

  std::mutex seq_mutex_;
  uint64_t next_seq_id_ = 1;
  std::mt19937_64 seq_len_gen_{77};

  std::vector<ShmRegion> shm_regions_;
  bool shm_ready_ = false;
};

}  // namespace tpuperf
