// Backend abstraction decoupling the perf harness from the service kind.
//
// Counterpart of the reference's client_backend layer
// (/root/reference/src/c++/perf_analyzer/client_backend/client_backend.h:
// 101-368): a factory + virtual interface so the load managers and profiler
// drive any endpoint kind. Kinds here: TPU_HTTP (our native HTTP client),
// TPU_CAPI (in-process engine via dlopen'd C-API shim — the reference's
// triton_c_api equivalent). gRPC joins when the native gRPC client lands.
// Unlike the reference, the interface reuses the tpuclient tensor types
// directly instead of wrapping them per backend — same-process types, no
// adapter cost.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "tpuclient/common.h"
#include "tpuclient/json.h"

namespace tpuperf {

enum class BackendKind {
  TPU_HTTP,
  TPU_GRPC,
  TPU_CAPI,
  // Non-TPU service kinds, for harness parity with the reference's four-way
  // abstraction (client_backend.h:101-106): TFS PredictionService over the
  // in-tree gRPC transport, and TorchServe's prediction REST API.
  TENSORFLOW_SERVING,
  TORCHSERVE,
};

// Server-side per-model statistics snapshot (reference ModelStatistics,
// client_backend.h:148-168), pulled from the v2 statistics endpoint.
struct ModelStatistics {
  uint64_t success_count = 0;
  uint64_t inference_count = 0;
  uint64_t execution_count = 0;
  uint64_t queue_time_ns = 0;
  uint64_t compute_input_time_ns = 0;
  uint64_t compute_infer_time_ns = 0;
  uint64_t compute_output_time_ns = 0;
  uint64_t cumulative_request_time_ns = 0;
};

class ClientBackend {
 public:
  virtual ~ClientBackend() = default;

  virtual tpuclient::Error ServerExtensions(
      std::vector<std::string>* extensions) = 0;
  virtual tpuclient::Error ModelMetadata(tpuclient::JsonPtr* metadata,
                                         const std::string& model_name,
                                         const std::string& version) = 0;
  virtual tpuclient::Error ModelConfig(tpuclient::JsonPtr* config,
                                       const std::string& model_name,
                                       const std::string& version) = 0;

  virtual tpuclient::Error Infer(
      tpuclient::InferResult** result, const tpuclient::InferOptions& options,
      const std::vector<tpuclient::InferInput*>& inputs,
      const std::vector<const tpuclient::InferRequestedOutput*>& outputs) = 0;

  virtual tpuclient::Error AsyncInfer(
      tpuclient::OnCompleteFn callback, const tpuclient::InferOptions& options,
      const std::vector<tpuclient::InferInput*>& inputs,
      const std::vector<const tpuclient::InferRequestedOutput*>& outputs) = 0;

  // model_name -> stats; empty name = all models (ensemble rollup pulls the
  // composing models from the same snapshot).
  virtual tpuclient::Error ModelInferenceStatistics(
      std::map<std::string, ModelStatistics>* stats,
      const std::string& model_name = "") = 0;

  virtual tpuclient::Error ClientInferStat(tpuclient::InferStat* stat) = 0;

  // Shared-memory control plane (system shm data plane for request tensors;
  // reference client_backend.h:330-368).
  virtual tpuclient::Error RegisterSystemSharedMemory(const std::string& name,
                                                      const std::string& key,
                                                      size_t byte_size);
  virtual tpuclient::Error UnregisterSystemSharedMemory(
      const std::string& name);
  // TPU-shm data plane (the cudashm counterpart, reference
  // client_backend.h:341-356): raw_handle carries the serialized region
  // handle, exactly as the reference transports cudaIpcMemHandle_t bytes.
  virtual tpuclient::Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size);
  virtual tpuclient::Error UnregisterTpuSharedMemory(const std::string& name);

  virtual bool SupportsAsync() const { return true; }

  // Bidirectional streaming (reference main.cc:610-748 drives sequence
  // models over one gRPC stream with --streaming; only the TPU_GRPC kind
  // implements it here). The callback fires once per STREAM RESPONSE — a
  // decoupled model emits several per request, the last one carrying the
  // triton_final_response parameter.
  virtual bool SupportsStreaming() const { return false; }
  virtual tpuclient::Error StartStream(tpuclient::OnCompleteFn callback) {
    (void)callback;
    return tpuclient::Error(
        "streaming is not supported by this service kind");
  }
  virtual tpuclient::Error AsyncStreamInfer(
      const tpuclient::InferOptions& options,
      const std::vector<tpuclient::InferInput*>& inputs,
      const std::vector<const tpuclient::InferRequestedOutput*>& outputs) {
    (void)options;
    (void)inputs;
    (void)outputs;
    return tpuclient::Error(
        "streaming is not supported by this service kind");
  }
  virtual tpuclient::Error StopStream() {
    return tpuclient::Error(
        "streaming is not supported by this service kind");
  }
};

// True when this stream response terminates its request: the response
// carries no triton_final_response parameter (non-decoupled model — one
// response per request) or carries it set. Implemented by the gRPC kind.
bool IsFinalStreamResponse(tpuclient::InferResult* result);

class ClientBackendFactory {
 public:
  ClientBackendFactory(BackendKind kind, std::string url, bool verbose,
                       size_t max_async_concurrency = 8)
      : kind_(kind), url_(std::move(url)), verbose_(verbose),
        max_async_concurrency_(max_async_concurrency) {}

  // TPU_CAPI parameters: path to libtpuserver.so, comma-separated model-zoo
  // names to host, and the repo root for the embedded interpreter's
  // sys.path (reference triton_c_api takes the triton library dir the same
  // way, main.cc:1253-1266).
  void SetCApiOptions(std::string lib_path, std::string models,
                      std::string repo_root) {
    capi_lib_path_ = std::move(lib_path);
    capi_models_ = std::move(models);
    capi_repo_root_ = std::move(repo_root);
  }

  tpuclient::Error Create(std::unique_ptr<ClientBackend>* backend) const;

  BackendKind Kind() const { return kind_; }

 private:
  BackendKind kind_;
  std::string url_;
  bool verbose_;
  size_t max_async_concurrency_;
  std::string capi_lib_path_;
  std::string capi_models_;
  std::string capi_repo_root_;
};

// Parses a v2 statistics body ({"model_stats": [...]}) into the per-model
// map; shared by the HTTP and C-API backends.
tpuclient::Error ParseModelStatsJson(
    const tpuclient::JsonPtr& body,
    std::map<std::string, ModelStatistics>* stats);

// Defined in capi_backend.cc.
tpuclient::Error CreateCApiBackend(const std::string& lib_path,
                                   const std::string& models,
                                   const std::string& repo_root,
                                   std::unique_ptr<ClientBackend>* backend);

// Defined in grpc_backend.cc.
tpuclient::Error CreateTfServeBackend(
    const std::string& url, bool verbose,
    std::unique_ptr<ClientBackend>* backend);
// Override the TFS PredictionService signature ("serving_default" by
// default; reference --model-signature-name).  Process-wide: the CLI sets
// it once, before any backend exists.  Defined in tfserve_backend.cc.
void SetTfServeSignatureName(const std::string& name);
tpuclient::Error CreateTorchServeBackend(
    const std::string& url, bool verbose,
    std::unique_ptr<ClientBackend>* backend);
tpuclient::Error CreateGrpcBackend(const std::string& url, bool verbose,
                                   std::unique_ptr<ClientBackend>* backend);

}  // namespace tpuperf
