// Shared enums + helpers for the perf harness.
//
// Counterpart of the reference's perf_utils.{h,cc}
// (/root/reference/src/c++/perf_analyzer/perf_utils.h:53-146): per-request
// timestamp tuples, load-distribution/search-mode/shm-type enums, and the
// inter-arrival schedule distribution generators.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <random>
#include <string>
#include <vector>

namespace tpuperf {

// Cooperative early-exit flag: set by the CLI's SIGINT handler, polled by
// the profiler's measurement loops so Ctrl-C drains gracefully (reference
// main.cc:42-55 `early_exit`).
inline std::atomic<bool>& EarlyExit() {
  static std::atomic<bool> flag{false};
  return flag;
}

// (start_ns, end_ns, sequence_end, delayed) — reference TimestampVector
// tuple (perf_utils.h:53-54).
struct RequestRecord {
  uint64_t start_ns = 0;
  uint64_t end_ns = 0;
  bool sequence_end = false;
  bool delayed = false;
};

using TimestampVector = std::vector<RequestRecord>;

enum class Distribution { POISSON, CONSTANT, CUSTOM };
enum class SearchMode { LINEAR, BINARY, NONE };
enum class SharedMemoryType { NONE, SYSTEM, TPU };
enum class MeasurementMode { TIME_WINDOWS, COUNT_WINDOWS };

inline uint64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Inter-arrival schedule generator (reference ScheduleDistribution template,
// perf_utils.h:144-146): returns nanosecond gaps for the given request rate.
class ScheduleDistribution {
 public:
  ScheduleDistribution(Distribution kind, double rate_per_sec, uint64_t seed)
      : kind_(kind), gen_(seed) {
    period_ns_ = rate_per_sec > 0 ? 1e9 / rate_per_sec : 0;
    exp_ = std::exponential_distribution<double>(
        rate_per_sec > 0 ? rate_per_sec / 1e9 : 1.0);
  }

  uint64_t NextGapNs() {
    if (kind_ == Distribution::POISSON) {
      return static_cast<uint64_t>(exp_(gen_));
    }
    return static_cast<uint64_t>(period_ns_);
  }

 private:
  Distribution kind_;
  std::mt19937_64 gen_;
  double period_ns_;
  std::exponential_distribution<double> exp_;
};

}  // namespace tpuperf
