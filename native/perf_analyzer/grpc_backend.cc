// Kind=TPU_GRPC: perf harness over the native gRPC client (in-tree HTTP/2
// transport). Counterpart of the reference's protocol-switched Triton
// backend (triton_client_backend.h:61-199 holds both HTTP and gRPC clients;
// here each protocol is its own kind selected by -i/--service-kind).

#include "client_backend.h"
#include "tpuclient/grpc_client.h"

using tpuclient::Error;
using tpuclient::JsonPtr;

namespace tpuperf {

namespace {

// Converts a protobuf-typed response into the in-tree JSON DOM so the
// model parser / profiler consume one shape regardless of protocol.
JsonPtr TensorMetaToJson(const std::string& name, const std::string& dtype,
                         const google::protobuf::RepeatedField<int64_t>&
                             shape) {
  JsonPtr t = tpuclient::Json::MakeObject();
  t->Set("name", name);
  t->Set("datatype", dtype);
  JsonPtr dims = tpuclient::Json::MakeArray();
  for (int64_t d : shape) dims->Append(tpuclient::Json::MakeInt(d));
  t->Set("shape", dims);
  return t;
}

class GrpcClientBackend : public ClientBackend {
 public:
  static Error Create(const std::string& url, bool verbose,
                      std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<GrpcClientBackend>(new GrpcClientBackend());
    Error err = tpuclient::InferenceServerGrpcClient::Create(&b->client_, url,
                                                             verbose);
    if (!err.IsOk()) return err;
    *backend = std::move(b);
    return Error::Success();
  }

  Error ServerExtensions(std::vector<std::string>* extensions) override {
    inference::ServerMetadataResponse meta;
    Error err = client_->ServerMetadata(&meta);
    if (!err.IsOk()) return err;
    extensions->assign(meta.extensions().begin(), meta.extensions().end());
    return Error::Success();
  }

  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string& version) override {
    inference::ModelMetadataResponse meta;
    Error err = client_->ModelMetadata(&meta, model_name, version);
    if (!err.IsOk()) return err;
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", meta.name());
    out->Set("platform", meta.platform());
    JsonPtr versions = tpuclient::Json::MakeArray();
    for (const auto& v : meta.versions())
      versions->Append(tpuclient::Json::MakeString(v));
    out->Set("versions", versions);
    JsonPtr inputs = tpuclient::Json::MakeArray();
    for (const auto& io : meta.inputs())
      inputs->Append(TensorMetaToJson(io.name(), io.datatype(), io.shape()));
    out->Set("inputs", inputs);
    JsonPtr outputs = tpuclient::Json::MakeArray();
    for (const auto& io : meta.outputs())
      outputs->Append(TensorMetaToJson(io.name(), io.datatype(), io.shape()));
    out->Set("outputs", outputs);
    *metadata = out;
    return Error::Success();
  }

  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string& version) override {
    inference::ModelConfigResponse resp;
    Error err = client_->ModelConfig(&resp, model_name, version);
    if (!err.IsOk()) return err;
    const inference::ModelConfig& c = resp.config();
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", c.name());
    out->Set("platform", c.platform());
    out->Set("max_batch_size", int64_t(c.max_batch_size()));
    if (c.has_dynamic_batching()) {
      JsonPtr db = tpuclient::Json::MakeObject();
      JsonPtr preferred = tpuclient::Json::MakeArray();
      for (int32_t p : c.dynamic_batching().preferred_batch_size())
        preferred->Append(tpuclient::Json::MakeInt(p));
      db->Set("preferred_batch_size", preferred);
      db->Set("max_queue_delay_microseconds",
              uint64_t(c.dynamic_batching().max_queue_delay_microseconds()));
      out->Set("dynamic_batching", db);
    }
    if (c.has_sequence_batching()) {
      out->Set("sequence_batching", tpuclient::Json::MakeObject());
    }
    if (c.has_model_transaction_policy() &&
        c.model_transaction_policy().decoupled()) {
      JsonPtr mtp = tpuclient::Json::MakeObject();
      mtp->Set("decoupled", true);
      out->Set("model_transaction_policy", mtp);
    }
    if (c.has_ensemble_scheduling()) {
      JsonPtr ens = tpuclient::Json::MakeObject();
      JsonPtr steps = tpuclient::Json::MakeArray();
      for (const auto& step : c.ensemble_scheduling().step()) {
        JsonPtr s = tpuclient::Json::MakeObject();
        s->Set("model_name", step.model_name());
        steps->Append(s);
      }
      ens->Set("step", steps);
      out->Set("ensemble_scheduling", ens);
    }
    *config = out;
    return Error::Success();
  }

  Error Infer(tpuclient::InferResult** result,
              const tpuclient::InferOptions& options,
              const std::vector<tpuclient::InferInput*>& inputs,
              const std::vector<const tpuclient::InferRequestedOutput*>&
                  outputs) override {
    return client_->Infer(result, options, inputs, outputs);
  }

  Error AsyncInfer(tpuclient::OnCompleteFn callback,
                   const tpuclient::InferOptions& options,
                   const std::vector<tpuclient::InferInput*>& inputs,
                   const std::vector<const tpuclient::InferRequestedOutput*>&
                       outputs) override {
    return client_->AsyncInfer(std::move(callback), options, inputs, outputs);
  }

  bool SupportsStreaming() const override { return true; }

  Error StartStream(tpuclient::OnCompleteFn callback) override {
    return client_->StartStream(std::move(callback));
  }

  Error AsyncStreamInfer(
      const tpuclient::InferOptions& options,
      const std::vector<tpuclient::InferInput*>& inputs,
      const std::vector<const tpuclient::InferRequestedOutput*>& outputs)
      override {
    return client_->AsyncStreamInfer(options, inputs, outputs);
  }

  Error StopStream() override { return client_->StopStream(); }

  Error ModelInferenceStatistics(std::map<std::string, ModelStatistics>* stats,
                                 const std::string& model_name) override {
    inference::ModelStatisticsResponse resp;
    Error err = client_->ModelInferenceStatistics(&resp, model_name);
    if (!err.IsOk()) return err;
    stats->clear();
    for (const auto& m : resp.model_stats()) {
      ModelStatistics ms;
      ms.inference_count = m.inference_count();
      ms.execution_count = m.execution_count();
      ms.success_count = m.inference_stats().success().count();
      ms.cumulative_request_time_ns = m.inference_stats().success().ns();
      ms.queue_time_ns = m.inference_stats().queue().ns();
      ms.compute_input_time_ns = m.inference_stats().compute_input().ns();
      ms.compute_infer_time_ns = m.inference_stats().compute_infer().ns();
      ms.compute_output_time_ns = m.inference_stats().compute_output().ns();
      (*stats)[m.name()] = ms;
    }
    return Error::Success();
  }

  Error ClientInferStat(tpuclient::InferStat* stat) override {
    return client_->ClientInferStat(stat);
  }

  Error RegisterSystemSharedMemory(const std::string& name,
                                   const std::string& key,
                                   size_t byte_size) override {
    return client_->RegisterSystemSharedMemory(name, key, byte_size);
  }

  Error UnregisterSystemSharedMemory(const std::string& name) override {
    return client_->UnregisterSystemSharedMemory(name);
  }

  Error RegisterTpuSharedMemory(const std::string& name,
                                const std::string& raw_handle,
                                int64_t device_id,
                                size_t byte_size) override {
    return client_->RegisterTpuSharedMemory(name, raw_handle, device_id,
                                            byte_size);
  }

  Error UnregisterTpuSharedMemory(const std::string& name) override {
    return client_->UnregisterTpuSharedMemory(name);
  }

 private:
  GrpcClientBackend() = default;
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client_;
};

}  // namespace

Error CreateGrpcBackend(const std::string& url, bool verbose,
                        std::unique_ptr<ClientBackend>* backend) {
  return GrpcClientBackend::Create(url, verbose, backend);
}

bool IsFinalStreamResponse(tpuclient::InferResult* result) {
  if (result == nullptr) return true;
  // Error results carry no response proto (InferResultGrpc is built with a
  // null message on stream errors) — they terminate their request.
  if (!result->RequestStatus().IsOk()) return true;
  auto* g = dynamic_cast<tpuclient::InferResultGrpc*>(result);
  if (g == nullptr) return true;  // non-gRPC results: one-shot
  const auto& params = g->Response().parameters();
  auto it = params.find("triton_final_response");
  if (it == params.end()) return true;  // non-decoupled model
  return it->second.bool_param();
}

}  // namespace tpuperf
