// Search driver + measurement statistics.
//
// Counterpart of the reference's inference_profiler.{h,cc}
// (/root/reference/src/c++/perf_analyzer/inference_profiler.h:71-238,
// .cc:441-960): sweeps concurrency or request rate (linear or binary
// search), takes measurements over time- or count-windows, detects
// stability over a 3-window history (±threshold on both throughput and
// latency), and merges client-side timestamps with server-side stat deltas
// (queue / compute phases, ensemble composing-model rollup).
#pragma once

#include <map>
#include <memory>
#include <vector>

#include "concurrency_manager.h"
#include "custom_load_manager.h"
#include "request_rate_manager.h"

namespace tpuperf {

struct ServerSideStats {
  uint64_t inference_count = 0;
  uint64_t execution_count = 0;
  uint64_t success_count = 0;
  uint64_t queue_time_ns = 0;
  uint64_t compute_input_time_ns = 0;
  uint64_t compute_infer_time_ns = 0;
  uint64_t compute_output_time_ns = 0;
  uint64_t cumulative_request_time_ns = 0;
  // ensemble composing-model breakdown (reference ServerSideStats map,
  // inference_profiler.h:71-82)
  std::map<std::string, ServerSideStats> composing;
};

struct ClientSideStats {
  uint64_t request_count = 0;
  double infer_per_sec = 0;
  double sequence_per_sec = 0;
  uint64_t avg_latency_ns = 0;
  uint64_t std_latency_ns = 0;
  std::map<size_t, uint64_t> percentile_latency_ns;  // 50/90/95/99
  uint64_t avg_send_time_ns = 0;
  uint64_t avg_receive_time_ns = 0;
  size_t delayed_request_count = 0;
  uint64_t duration_ns = 0;
};

struct PerfStatus {
  size_t concurrency = 0;
  double request_rate = 0;
  ClientSideStats client_stats;
  ServerSideStats server_stats;
  size_t batch_size = 1;
  bool on_sequence_model = false;
  // latency used for stability/threshold decisions (avg or percentile)
  uint64_t stabilizing_latency_ns = 0;
};

class InferenceProfiler {
 public:
  struct Options {
    double stability_threshold = 0.1;    // ±10%
    uint64_t measurement_window_ms = 5000;
    MeasurementMode measurement_mode = MeasurementMode::TIME_WINDOWS;
    uint64_t measurement_request_count = 50;
    size_t max_trials = 10;
    uint64_t latency_threshold_us = 0;   // 0 = no limit
    size_t stable_window = 3;
    int64_t percentile = -1;             // -1 = use average latency
    bool verbose = false;
  };

  InferenceProfiler(const Options& options,
                    std::shared_ptr<ModelParser> parser,
                    std::unique_ptr<ClientBackend> stats_backend,
                    LoadManager* manager);

  // Concurrency sweep (manager must be a ConcurrencyManager).
  tpuclient::Error ProfileConcurrency(size_t start, size_t end, size_t step,
                                      bool binary_search,
                                      std::vector<PerfStatus>* results);
  // Request-rate sweep (manager must be a RequestRateManager).
  tpuclient::Error ProfileRate(double start, double end, double step,
                               bool binary_search,
                               std::vector<PerfStatus>* results);
  // Custom intervals: single measurement at the file-implied rate.
  tpuclient::Error ProfileCustom(std::vector<PerfStatus>* results);

 private:
  // Measure until stable or max_trials (reference ProfileHelper,
  // inference_profiler.cc:441-566). `meets_threshold` false when the
  // latency limit was exceeded (search should stop descending/ascending).
  tpuclient::Error ProfileOnce(PerfStatus* status, bool* meets_threshold);

  // One measurement window (reference Measure, inference_profiler.cc:
  // 584-636): server stat delta + client stat delta + timestamp swap.
  tpuclient::Error Measure(PerfStatus* status);

  tpuclient::Error GetServerSideStats(
      std::map<std::string, ModelStatistics>* stats);

  void SummarizeClient(const TimestampVector& timestamps,
                       const tpuclient::InferStat& start_stat,
                       const tpuclient::InferStat& end_stat,
                       uint64_t duration_ns, size_t batch_size,
                       ClientSideStats* stats);
  void SummarizeServer(const std::map<std::string, ModelStatistics>& start,
                       const std::map<std::string, ModelStatistics>& end,
                       ServerSideStats* stats);

  Options options_;
  std::shared_ptr<ModelParser> parser_;
  std::unique_ptr<ClientBackend> stats_backend_;
  LoadManager* manager_;
};

}  // namespace tpuperf
