// Kind=TORCHSERVE: HTTP-only mini-client for TorchServe's inference REST
// API (POST /predictions/<model>).
//
// Counterpart of the reference's torchserve backend
// (/root/reference/src/c++/perf_analyzer/client_backend/torchserve/
// torchserve_client_backend.h:52-89, torchserve_http_client.{h,cc};
// requires --input-data with file paths, main.cc:1210-1216). TorchServe
// exposes no model metadata, so the backend synthesizes the single-BYTES
// "TORCHSERVE_INPUT" tensor the reference's InitTorchServe hardcodes
// (model_parser.cc:298-317) — as v2 JSON here, so the generic parser path
// applies. The BYTES element carries the path of the file to upload.

#include <cstring>
#include <fstream>
#include <sstream>

#include "client_backend.h"
#include "tpuclient/http_client.h"

using tpuclient::Error;
using tpuclient::JsonPtr;

namespace tpuperf {

namespace {

class TorchServeInferResult : public tpuclient::InferResult {
 public:
  TorchServeInferResult(std::string body, Error status, std::string model,
                        std::string request_id)
      : body_(std::move(body)), status_(std::move(status)),
        model_(std::move(model)), request_id_(std::move(request_id)) {}

  Error ModelName(std::string* name) const override {
    *name = model_;
    return Error::Success();
  }
  Error ModelVersion(std::string* version) const override {
    version->clear();
    return Error::Success();
  }
  Error Id(std::string* id) const override {
    *id = request_id_;
    return Error::Success();
  }
  Error Shape(const std::string&, std::vector<int64_t>* shape) const override {
    // The prediction body is opaque (model-dependent JSON/bytes).
    *shape = {int64_t(body_.size())};
    return Error::Success();
  }
  Error Datatype(const std::string&, std::string* datatype) const override {
    *datatype = "BYTES";
    return Error::Success();
  }
  Error RawData(const std::string&, const uint8_t** buf,
                size_t* byte_size) const override {
    *buf = reinterpret_cast<const uint8_t*>(body_.data());
    *byte_size = body_.size();
    return Error::Success();
  }
  Error RequestStatus() const override { return status_; }
  std::string DebugString() const override { return body_; }

 private:
  std::string body_;
  Error status_;
  std::string model_;
  std::string request_id_;
};

class TorchServeClientBackend : public ClientBackend {
 public:
  static Error Create(const std::string& url, bool verbose,
                      std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<TorchServeClientBackend>(
        new TorchServeClientBackend());
    // TorchServe inference API default port is 8080; honor explicit ports.
    std::string host;
    int port;
    tpuclient::SplitUrl(url, /*default_port=*/8080, &host, &port);
    Error err = tpuclient::InferenceServerHttpClient::Create(
        &b->client_, host + ":" + std::to_string(port), verbose);
    if (!err.IsOk()) return err;
    *backend = std::move(b);
    return Error::Success();
  }

  Error ServerExtensions(std::vector<std::string>* extensions) override {
    extensions->clear();
    return Error::Success();
  }

  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string&) override {
    // Synthesized: TorchServe returns no metadata (reference
    // model_parser.cc:302-314).
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", model_name);
    out->Set("platform", "torchserve");
    JsonPtr inputs = tpuclient::Json::MakeArray();
    JsonPtr in = tpuclient::Json::MakeObject();
    in->Set("name", "TORCHSERVE_INPUT");
    in->Set("datatype", "BYTES");
    JsonPtr dims = tpuclient::Json::MakeArray();
    dims->Append(tpuclient::Json::MakeInt(1));
    in->Set("shape", dims);
    inputs->Append(in);
    out->Set("inputs", inputs);
    out->Set("outputs", tpuclient::Json::MakeArray());
    *metadata = out;
    return Error::Success();
  }

  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string&) override {
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", model_name);
    out->Set("max_batch_size", int64_t(0));
    *config = out;
    return Error::Success();
  }

  Error Infer(tpuclient::InferResult** result,
              const tpuclient::InferOptions& options,
              const std::vector<tpuclient::InferInput*>& inputs,
              const std::vector<const tpuclient::InferRequestedOutput*>&)
      override {
    if (inputs.size() != 1)
      return Error("torchserve expects exactly one BYTES input holding the "
                   "file path (--input-data json)",
                   400);
    // Decode the first element of the length-prefixed BYTES stream: the
    // path of the file to upload (reference torchserve flow).
    std::string flat;
    inputs[0]->CopyTo(&flat);
    if (flat.size() < 4)
      return Error("empty TORCHSERVE_INPUT", 400);
    uint32_t len;
    memcpy(&len, flat.data(), 4);
    if (4 + size_t(len) > flat.size())
      return Error("malformed TORCHSERVE_INPUT BYTES element", 400);
    std::string path = flat.substr(4, len);

    // Cache file contents per path: the --input-data path set is fixed for
    // the run, and re-reading inside the timed request path would charge
    // disk I/O to the measured latency.
    auto cached = file_cache_.find(path);
    if (cached == file_cache_.end()) {
      std::ifstream f(path, std::ios::binary);
      if (!f.good())
        return Error("torchserve input file '" + path + "' not readable",
                     400);
      std::ostringstream content;
      content << f.rdbuf();
      cached = file_cache_.emplace(path, content.str()).first;
    }

    // Raw-body POST (TorchServe accepts raw bodies alongside multipart
    // form uploads; the reference uses the multipart form).
    JsonPtr resp;
    Error err = client_->Post("/predictions/" + options.model_name,
                              cached->second, &resp);
    std::string body = resp != nullptr ? resp->Serialize() : "";
    *result = new TorchServeInferResult(std::move(body), err,
                                        options.model_name,
                                        options.request_id);
    return err;
  }

  Error AsyncInfer(tpuclient::OnCompleteFn, const tpuclient::InferOptions&,
                   const std::vector<tpuclient::InferInput*>&,
                   const std::vector<const tpuclient::InferRequestedOutput*>&)
      override {
    return Error("async is not supported with the torchserve kind", 400);
  }

  Error ModelInferenceStatistics(std::map<std::string, ModelStatistics>*,
                                 const std::string&) override {
    return Error("server-side statistics are not available from TorchServe",
                 400);
  }

  Error ClientInferStat(tpuclient::InferStat* stat) override {
    return client_->ClientInferStat(stat);
  }

  bool SupportsAsync() const override { return false; }

 private:
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client_;
  std::map<std::string, std::string> file_cache_;
};

}  // namespace

Error CreateTorchServeBackend(const std::string& url, bool verbose,
                              std::unique_ptr<ClientBackend>* backend) {
  return TorchServeClientBackend::Create(url, verbose, backend);
}

}  // namespace tpuperf
