// Closed-loop load: maintain N concurrent outstanding requests.
//
// Counterpart of the reference's concurrency_manager.{h,cc}
// (/root/reference/src/c++/perf_analyzer/concurrency_manager.cc:90-425):
// worker threads each own a context pool; sync mode blocks one request per
// thread, async mode keeps (concurrency / threads) requests in flight per
// thread with completion callbacks capturing end timestamps. Sequence models
// pin one live sequence per context.
#pragma once

#include "load_manager.h"

namespace tpuperf {

class ConcurrencyManager : public LoadManager {
 public:
  static tpuclient::Error Create(const LoadOptions& options,
                                 const ClientBackendFactory& factory,
                                 std::shared_ptr<ModelParser> parser,
                                 std::shared_ptr<DataLoader> data_loader,
                                 std::unique_ptr<ConcurrencyManager>* manager);
  ~ConcurrencyManager() override;

  // Reconfigures the worker fleet to hold `concurrency` requests in flight
  // (reference ChangeConcurrencyLevel, concurrency_manager.cc:90-146).
  tpuclient::Error ChangeConcurrencyLevel(size_t concurrency);

 private:
  ConcurrencyManager(const LoadOptions& options,
                     const ClientBackendFactory& factory,
                     std::shared_ptr<ModelParser> parser,
                     std::shared_ptr<DataLoader> data_loader)
      : LoadManager(options, factory, std::move(parser),
                    std::move(data_loader)) {}

  // per-thread target concurrency, adjusted by ChangeConcurrencyLevel
  struct Share {
    std::atomic<size_t> target{0};
  };

  // each worker holds its own shared_ptr to its Share: shares_ may grow
  // (push_back) while workers run, so workers never index the vector
  void WorkerLoop(std::shared_ptr<ThreadStat> stat,
                  std::shared_ptr<ThreadConfig> config,
                  std::shared_ptr<Share> share);

  std::vector<std::shared_ptr<Share>> shares_;
  std::mutex wake_mutex_;
  std::condition_variable wake_cv_;
};

}  // namespace tpuperf
