#include "model_parser.h"

using tpuclient::Error;
using tpuclient::Json;
using tpuclient::JsonPtr;

namespace tpuperf {

static void ParseTensors(const JsonPtr& list,
                         std::map<std::string, ModelTensor>* out) {
  if (!list || !list->IsArray()) return;
  for (size_t i = 0; i < list->Size(); ++i) {
    const JsonPtr& t = list->At(i);
    if (!t->IsObject()) continue;
    ModelTensor mt;
    JsonPtr name = t->Get("name");
    if (!name || !name->IsString()) continue;
    mt.name = name->AsString();
    JsonPtr dt = t->Get("datatype");
    if (dt && dt->IsString()) mt.datatype = dt->AsString();
    JsonPtr shape = t->Get("shape");
    if (shape && shape->IsArray()) {
      for (size_t j = 0; j < shape->Size(); ++j)
        mt.shape.push_back(shape->At(j)->AsInt());
    }
    JsonPtr opt = t->Get("optional");
    if (opt && opt->IsBool()) mt.is_optional = opt->AsBool();
    (*out)[mt.name] = mt;
  }
}

Error ModelParser::Init(const JsonPtr& metadata, const JsonPtr& config) {
  if (!metadata || !metadata->IsObject())
    return Error("model metadata is not a JSON object", 400);
  JsonPtr name = metadata->Get("name");
  if (!name || !name->IsString())
    return Error("model metadata missing 'name'", 400);
  name_ = name->AsString();
  JsonPtr versions = metadata->Get("versions");
  if (versions && versions->IsArray() && versions->Size() > 0 &&
      versions->At(versions->Size() - 1)->IsString()) {
    version_ = versions->At(versions->Size() - 1)->AsString();
  }

  ParseTensors(metadata->Get("inputs"), &inputs_);
  ParseTensors(metadata->Get("outputs"), &outputs_);

  if (!config || !config->IsObject())
    return Error("model config is not a JSON object", 400);
  JsonPtr mbs = config->Get("max_batch_size");
  if (mbs && mbs->IsNumber()) max_batch_size_ = mbs->AsInt();

  // metadata shapes include the batch dim when the model is batchable
  // (ModelConfig.metadata_dict prepends -1); strip it so the harness works
  // with per-request shapes.
  if (max_batch_size_ > 0) {
    for (auto* tensors : {&inputs_, &outputs_}) {
      for (auto& kv : *tensors) {
        if (!kv.second.shape.empty()) {
          kv.second.shape.erase(kv.second.shape.begin());
        }
      }
    }
  }

  bool has_sequence = config->Has("sequence_batching");
  bool has_dynamic = config->Has("dynamic_batching");
  bool has_ensemble = false;
  JsonPtr ens = config->Get("ensemble_scheduling");
  if (ens && ens->IsObject()) {
    JsonPtr steps = ens->Get("step");
    if (steps && steps->IsArray()) {
      has_ensemble = steps->Size() > 0;
      for (size_t i = 0; i < steps->Size(); ++i) {
        JsonPtr mn = steps->At(i)->Get("model_name");
        if (mn && mn->IsString()) composing_.insert(mn->AsString());
      }
    }
  }

  if (has_ensemble) {
    scheduler_ = has_sequence ? SchedulerType::ENSEMBLE_SEQUENCE
                              : SchedulerType::ENSEMBLE;
  } else if (has_sequence) {
    scheduler_ = SchedulerType::SEQUENCE;
  } else if (has_dynamic) {
    scheduler_ = SchedulerType::DYNAMIC;
  } else {
    scheduler_ = SchedulerType::NONE;
  }

  JsonPtr policy = config->Get("model_transaction_policy");
  if (policy && policy->IsObject()) {
    JsonPtr dec = policy->Get("decoupled");
    if (dec && dec->IsBool()) decoupled_ = dec->AsBool();
  }
  return Error::Success();
}

}  // namespace tpuperf
