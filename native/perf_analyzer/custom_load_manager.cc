#include "custom_load_manager.h"

#include <fstream>
#include <stdexcept>

using tpuclient::Error;

namespace tpuperf {

Error CustomLoadManager::Create(
    const LoadOptions& options, const std::string& intervals_file,
    const ClientBackendFactory& factory, std::shared_ptr<ModelParser> parser,
    std::shared_ptr<DataLoader> data_loader,
    std::unique_ptr<CustomLoadManager>* manager) {
  auto m = std::unique_ptr<CustomLoadManager>(new CustomLoadManager(
      options, intervals_file, factory, std::move(parser),
      std::move(data_loader)));
  Error err = m->InitCustomIntervals();
  if (!err.IsOk()) return err;
  *manager = std::move(m);
  return Error::Success();
}

Error CustomLoadManager::InitCustomIntervals() {
  std::ifstream f(intervals_file_);
  if (!f.good())
    return Error("cannot open intervals file '" + intervals_file_ + "'", 400);
  intervals_ns_.clear();
  std::string line;
  size_t line_no = 0;
  while (std::getline(f, line)) {
    ++line_no;
    if (line.find_first_not_of(" \t\r") == std::string::npos) continue;
    try {
      size_t used = 0;
      uint64_t v = std::stoull(line, &used);
      if (line.find_first_not_of(" \t\r", used) != std::string::npos) {
        throw std::invalid_argument("trailing characters");
      }
      intervals_ns_.push_back(v);
    } catch (const std::exception&) {
      return Error("intervals file '" + intervals_file_ + "' line " +
                       std::to_string(line_no) + " is not a nanosecond "
                       "integer: '" + line + "'",
                   400);
    }
  }
  if (intervals_ns_.empty())
    return Error("intervals file '" + intervals_file_ + "' is empty", 400);
  return Error::Success();
}

Error CustomLoadManager::GetCustomRequestRate(double* request_rate) {
  if (intervals_ns_.empty()) return Error("no intervals loaded", 400);
  uint64_t total = 0;
  for (uint64_t v : intervals_ns_) total += v;
  if (total == 0) return Error("intervals sum to zero", 400);
  *request_rate =
      static_cast<double>(intervals_ns_.size()) * 1e9 / total;
  return Error::Success();
}

Error CustomLoadManager::GenerateSchedule(double /*request_rate*/) {
  auto schedule = std::make_shared<std::vector<uint64_t>>();
  uint64_t t = 0;
  for (uint64_t gap : intervals_ns_) {
    t += gap;
    schedule->push_back(t);
  }
  std::lock_guard<std::mutex> lk(wake_mutex_);
  schedule_ = std::move(schedule);
  return Error::Success();
}

Error CustomLoadManager::Start() {
  // the implied average rate sizes the worker fleet; the schedule itself
  // comes verbatim from the file
  double rate = 1.0;
  Error err = GetCustomRequestRate(&rate);
  if (!err.IsOk()) return err;
  return ChangeRequestRate(rate);
}

}  // namespace tpuperf
