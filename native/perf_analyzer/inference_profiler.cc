#include "inference_profiler.h"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <thread>

using tpuclient::Error;

namespace tpuperf {

InferenceProfiler::InferenceProfiler(
    const Options& options, std::shared_ptr<ModelParser> parser,
    std::unique_ptr<ClientBackend> stats_backend, LoadManager* manager)
    : options_(options), parser_(std::move(parser)),
      stats_backend_(std::move(stats_backend)), manager_(manager) {}

Error InferenceProfiler::GetServerSideStats(
    std::map<std::string, ModelStatistics>* stats) {
  // pull the full snapshot so ensemble composing models come along
  return stats_backend_->ModelInferenceStatistics(stats, "");
}

Error InferenceProfiler::Measure(PerfStatus* status) {
  std::map<std::string, ModelStatistics> server_start, server_end;
  tpuclient::InferStat client_start, client_end;

  Error err = GetServerSideStats(&server_start);
  bool have_server_stats = err.IsOk();
  err = manager_->GetAccumulatedClientStat(&client_start);
  if (!err.IsOk()) return err;
  // drop records from before this window
  TimestampVector discard;
  manager_->SwapTimestamps(&discard);

  uint64_t window_start = NowNs();
  if (options_.measurement_mode == MeasurementMode::TIME_WINDOWS) {
    // sleep 1.2x the window so in-flight tails complete (reference
    // inference_profiler.cc:602); chunked so SIGINT drains promptly
    uint64_t remaining_ms = options_.measurement_window_ms * 12 / 10;
    while (remaining_ms > 0 && !EarlyExit().load()) {
      uint64_t chunk = std::min<uint64_t>(remaining_ms, 100);
      std::this_thread::sleep_for(std::chrono::milliseconds(chunk));
      remaining_ms -= chunk;
    }
  } else {
    while (manager_->CountCollectedRequests() <
               options_.measurement_request_count &&
           !EarlyExit().load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
      Error health = manager_->CheckHealth();
      if (!health.IsOk()) return health;
    }
  }
  uint64_t window_end = NowNs();

  err = manager_->CheckHealth();
  if (!err.IsOk()) return err;

  if (have_server_stats) {
    err = GetServerSideStats(&server_end);
    if (!err.IsOk()) have_server_stats = false;
  }
  err = manager_->GetAccumulatedClientStat(&client_end);
  if (!err.IsOk()) return err;
  TimestampVector timestamps;
  manager_->SwapTimestamps(&timestamps);

  status->batch_size = manager_->BatchSize();
  SummarizeClient(timestamps, client_start, client_end,
                  window_end - window_start, status->batch_size,
                  &status->client_stats);
  if (have_server_stats) {
    SummarizeServer(server_start, server_end, &status->server_stats);
  }

  if (options_.percentile > 0) {
    auto it = status->client_stats.percentile_latency_ns.find(
        static_cast<size_t>(options_.percentile));
    status->stabilizing_latency_ns =
        it != status->client_stats.percentile_latency_ns.end()
            ? it->second
            : status->client_stats.avg_latency_ns;
  } else {
    status->stabilizing_latency_ns = status->client_stats.avg_latency_ns;
  }
  return Error::Success();
}

void InferenceProfiler::SummarizeClient(const TimestampVector& timestamps,
                                        const tpuclient::InferStat& start_stat,
                                        const tpuclient::InferStat& end_stat,
                                        uint64_t duration_ns,
                                        size_t batch_size,
                                        ClientSideStats* stats) {
  *stats = ClientSideStats();
  stats->duration_ns = duration_ns;
  stats->request_count = timestamps.size();
  if (timestamps.empty() || duration_ns == 0) return;

  std::vector<uint64_t> latencies;
  latencies.reserve(timestamps.size());
  size_t sequence_ends = 0;
  for (const auto& r : timestamps) {
    latencies.push_back(r.end_ns - r.start_ns);
    if (r.sequence_end) sequence_ends++;
    if (r.delayed) stats->delayed_request_count++;
  }
  std::sort(latencies.begin(), latencies.end());

  double seconds = duration_ns / 1e9;
  // Each request carries batch_size inferences (reference SummarizeClientStat
  // computes valid_request_count * batch / duration, inference_profiler.cc:812).
  stats->infer_per_sec = timestamps.size() * batch_size / seconds;
  stats->sequence_per_sec = sequence_ends / seconds;

  uint64_t total = 0;
  for (uint64_t l : latencies) total += l;
  stats->avg_latency_ns = total / latencies.size();
  double var = 0;
  for (uint64_t l : latencies) {
    double d = static_cast<double>(l) - stats->avg_latency_ns;
    var += d * d;
  }
  stats->std_latency_ns = static_cast<uint64_t>(
      std::sqrt(var / latencies.size()));
  for (size_t p : {50, 90, 95, 99}) {
    // Nearest-rank percentile: ceil(N*p/100) ranks, 0-based index.
    size_t rank = (latencies.size() * p + 99) / 100;
    size_t idx = std::min(latencies.size() - 1, rank > 0 ? rank - 1 : 0);
    stats->percentile_latency_ns[p] = latencies[idx];
  }

  uint64_t req_delta =
      end_stat.completed_request_count - start_stat.completed_request_count;
  if (req_delta > 0) {
    stats->avg_send_time_ns =
        (end_stat.cumulative_send_time_ns - start_stat.cumulative_send_time_ns) /
        req_delta;
    stats->avg_receive_time_ns = (end_stat.cumulative_receive_time_ns -
                                  start_stat.cumulative_receive_time_ns) /
                                 req_delta;
  }
}

static ServerSideStats DiffStats(const ModelStatistics& a,
                                 const ModelStatistics& b) {
  ServerSideStats out;
  out.inference_count = b.inference_count - a.inference_count;
  out.execution_count = b.execution_count - a.execution_count;
  out.success_count = b.success_count - a.success_count;
  out.queue_time_ns = b.queue_time_ns - a.queue_time_ns;
  out.compute_input_time_ns = b.compute_input_time_ns - a.compute_input_time_ns;
  out.compute_infer_time_ns = b.compute_infer_time_ns - a.compute_infer_time_ns;
  out.compute_output_time_ns =
      b.compute_output_time_ns - a.compute_output_time_ns;
  out.cumulative_request_time_ns =
      b.cumulative_request_time_ns - a.cumulative_request_time_ns;
  return out;
}

void InferenceProfiler::SummarizeServer(
    const std::map<std::string, ModelStatistics>& start,
    const std::map<std::string, ModelStatistics>& end, ServerSideStats* stats) {
  *stats = ServerSideStats();
  auto diff_model = [&](const std::string& name, ServerSideStats* out) {
    auto it_end = end.find(name);
    if (it_end == end.end()) return;
    ModelStatistics zero;
    auto it_start = start.find(name);
    *out = DiffStats(it_start != start.end() ? it_start->second : zero,
                     it_end->second);
  };
  diff_model(parser_->Name(), stats);
  for (const auto& composing : parser_->ComposingModels()) {
    ServerSideStats child;
    diff_model(composing, &child);
    stats->composing[composing] = child;
  }
}

Error InferenceProfiler::ProfileOnce(PerfStatus* status,
                                     bool* meets_threshold) {
  *meets_threshold = true;
  std::vector<PerfStatus> history;
  for (size_t trial = 0; trial < options_.max_trials; ++trial) {
    if (EarlyExit().load()) return Error::Success();
    PerfStatus measurement = *status;
    Error err = Measure(&measurement);
    if (!err.IsOk()) return err;
    if (measurement.client_stats.request_count == 0) continue;
    history.push_back(measurement);
    *status = measurement;

    if (options_.verbose) {
      fprintf(stderr, "  trial %zu: %.1f infer/sec, avg latency %.0f usec\n",
              trial + 1, measurement.client_stats.infer_per_sec,
              measurement.client_stats.avg_latency_ns / 1e3);
    }

    if (options_.latency_threshold_us > 0 &&
        measurement.stabilizing_latency_ns >
            options_.latency_threshold_us * 1000) {
      *meets_threshold = false;
      return Error::Success();
    }
    if (history.size() >= options_.stable_window) {
      // stability: max deviation from the window mean within threshold on
      // BOTH throughput and latency (reference inference_profiler.cc:503-547)
      double ips_sum = 0, lat_sum = 0;
      size_t n = options_.stable_window;
      for (size_t i = history.size() - n; i < history.size(); ++i) {
        ips_sum += history[i].client_stats.infer_per_sec;
        lat_sum += static_cast<double>(history[i].stabilizing_latency_ns);
      }
      double ips_avg = ips_sum / n, lat_avg = lat_sum / n;
      bool stable = true;
      for (size_t i = history.size() - n; i < history.size(); ++i) {
        if (std::abs(history[i].client_stats.infer_per_sec - ips_avg) >
            options_.stability_threshold * ips_avg)
          stable = false;
        if (std::abs(static_cast<double>(history[i].stabilizing_latency_ns) -
                     lat_avg) > options_.stability_threshold * lat_avg)
          stable = false;
      }
      if (stable) return Error::Success();
    }
  }
  // not stable within max_trials: keep the last measurement, warn
  fprintf(stderr,
          "warning: measurement did not stabilize within %zu trials\n",
          options_.max_trials);
  return Error::Success();
}

Error InferenceProfiler::ProfileConcurrency(size_t start, size_t end,
                                            size_t step, bool binary_search,
                                            std::vector<PerfStatus>* results) {
  auto* manager = dynamic_cast<ConcurrencyManager*>(manager_);
  if (manager == nullptr)
    return Error("concurrency profiling needs a ConcurrencyManager", 400);

  auto run_one = [&](size_t concurrency, PerfStatus* status,
                     bool* meets) -> Error {
    Error err = manager->ChangeConcurrencyLevel(concurrency);
    if (!err.IsOk()) return err;
    status->concurrency = concurrency;
    status->on_sequence_model =
        parser_->Scheduler() == ModelParser::SchedulerType::SEQUENCE ||
        parser_->Scheduler() == ModelParser::SchedulerType::ENSEMBLE_SEQUENCE;
    return ProfileOnce(status, meets);
  };

  if (!binary_search) {
    for (size_t c = start; c <= end; c += step) {
      PerfStatus status;
      bool meets = true;
      Error err = run_one(c, &status, &meets);
      if (!err.IsOk()) return err;
      results->push_back(status);
      if (!meets || EarlyExit().load()) break;
    }
    return Error::Success();
  }

  // binary search for the highest concurrency under the latency threshold
  size_t lo = start, hi = end;
  while (lo <= hi) {
    size_t mid = lo + (hi - lo) / 2;
    PerfStatus status;
    bool meets = true;
    Error err = run_one(mid, &status, &meets);
    if (!err.IsOk()) return err;
    results->push_back(status);
    if (EarlyExit().load()) break;
    if (meets) {
      if (mid == hi) break;
      lo = mid + 1;
    } else {
      if (mid == lo) break;
      hi = mid - 1;
    }
  }
  return Error::Success();
}

Error InferenceProfiler::ProfileRate(double start, double end, double step,
                                     bool binary_search,
                                     std::vector<PerfStatus>* results) {
  auto* manager = dynamic_cast<RequestRateManager*>(manager_);
  if (manager == nullptr)
    return Error("rate profiling needs a RequestRateManager", 400);

  auto run_one = [&](double rate, PerfStatus* status, bool* meets) -> Error {
    Error err = manager->ChangeRequestRate(rate);
    if (!err.IsOk()) return err;
    status->request_rate = rate;
    return ProfileOnce(status, meets);
  };

  if (!binary_search) {
    for (double r = start; r <= end + 1e-9; r += step) {
      PerfStatus status;
      bool meets = true;
      Error err = run_one(r, &status, &meets);
      if (!err.IsOk()) return err;
      results->push_back(status);
      if (!meets || EarlyExit().load()) break;
    }
    return Error::Success();
  }

  double lo = start, hi = end;
  if (hi - lo <= step / 2) {
    // Degenerate range (e.g. start == end): still take one measurement
    // instead of silently reporting nothing.
    PerfStatus status;
    bool meets = true;
    Error err = run_one(lo, &status, &meets);
    if (!err.IsOk()) return err;
    results->push_back(status);
    return Error::Success();
  }
  while (hi - lo > step / 2) {
    double mid = (lo + hi) / 2;
    PerfStatus status;
    bool meets = true;
    Error err = run_one(mid, &status, &meets);
    if (!err.IsOk()) return err;
    results->push_back(status);
    if (EarlyExit().load()) break;
    if (meets) lo = mid;
    else hi = mid;
  }
  return Error::Success();
}

Error InferenceProfiler::ProfileCustom(std::vector<PerfStatus>* results) {
  auto* manager = dynamic_cast<CustomLoadManager*>(manager_);
  if (manager == nullptr)
    return Error("custom profiling needs a CustomLoadManager", 400);
  Error err = manager->Start();
  if (!err.IsOk()) return err;
  PerfStatus status;
  manager->GetCustomRequestRate(&status.request_rate);
  bool meets = true;
  err = ProfileOnce(&status, &meets);
  if (!err.IsOk()) return err;
  results->push_back(status);
  return Error::Success();
}

}  // namespace tpuperf
