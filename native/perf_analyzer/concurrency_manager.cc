#include "concurrency_manager.h"

using tpuclient::Error;

namespace tpuperf {

Error ConcurrencyManager::Create(const LoadOptions& options,
                                 const ClientBackendFactory& factory,
                                 std::shared_ptr<ModelParser> parser,
                                 std::shared_ptr<DataLoader> data_loader,
                                 std::unique_ptr<ConcurrencyManager>* manager) {
  auto m = std::unique_ptr<ConcurrencyManager>(new ConcurrencyManager(
      options, factory, std::move(parser), std::move(data_loader)));
  *manager = std::move(m);
  return Error::Success();
}

ConcurrencyManager::~ConcurrencyManager() {
  exit_.store(true);
  wake_cv_.notify_all();
  StopWorkerThreads();
}

Error ConcurrencyManager::ChangeConcurrencyLevel(size_t concurrency) {
  // Thread fleet: one thread per in-flight request in sync mode (a blocking
  // Infer can't multiplex), contexts multiplexed per thread in async mode
  // (reference concurrency_manager.cc:90-146). Both are capped by
  // max_threads; sync mode warns because the cap silently limits the real
  // generated load.
  size_t n_threads = std::min(concurrency, options_.max_threads);
  if (!options_.async && concurrency > options_.max_threads) {
    fprintf(stderr,
            "warning: sync mode caps in-flight requests at --max-threads "
            "(%zu < requested %zu); use -a for higher concurrency\n",
            options_.max_threads, concurrency);
  }
  // spawn missing workers
  while (threads_.size() < n_threads) {
    size_t idx = threads_.size();
    auto stat = std::make_shared<ThreadStat>();
    auto config = std::make_shared<ThreadConfig>();
    config->index = idx;
    Error err = factory_.Create(&config->backend);
    if (!err.IsOk()) return err;
    if (options_.shm_type != SharedMemoryType::NONE && !shm_ready_) {
      err = InitSharedMemory(config->backend.get());
      if (!err.IsOk()) return err;
    }
    auto share = std::make_shared<Share>();
    thread_stats_.push_back(stat);
    thread_configs_.push_back(config);
    shares_.push_back(share);
    threads_.emplace_back(&ConcurrencyManager::WorkerLoop, this, stat, config,
                          share);
  }
  // distribute the concurrency over the fleet
  for (size_t i = 0; i < shares_.size(); ++i) {
    size_t share = 0;
    if (i < n_threads) {
      share = concurrency / n_threads + (i < concurrency % n_threads ? 1 : 0);
    }
    shares_[i]->target.store(share);
  }
  wake_cv_.notify_all();
  return Error::Success();
}

void ConcurrencyManager::WorkerLoop(std::shared_ptr<ThreadStat> stat,
                                    std::shared_ptr<ThreadConfig> config,
                                    std::shared_ptr<Share> share) {
  // Async completion accounting: callbacks decrement `ongoing` and record
  // the end timestamp (reference callback latency capture,
  // concurrency_manager.cc:182-219).
  auto ongoing = std::make_shared<std::atomic<size_t>>(0);

  while (!exit_.load()) {
    size_t target = share->target.load();
    if (target == 0) {
      std::unique_lock<std::mutex> lk(wake_mutex_);
      wake_cv_.wait_for(lk, std::chrono::milliseconds(50), [&]() {
        return exit_.load() || share->target.load() > 0;
      });
      continue;
    }

    if (!options_.async) {
      // sync: one blocking request per pass
      InferContext* ctx;
      if (config->ctxs.empty()) {
        Error err = MakeContext(config.get(), &ctx);
        if (!err.IsOk()) {
          std::lock_guard<std::mutex> lk(stat->mu);
          stat->status = err;
          return;
        }
      } else {
        ctx = config->ctxs[0].get();
      }
      Error err = PrepareRequest(ctx);
      if (err.IsOk()) {
        tpuclient::InferResult* result = nullptr;
        uint64_t start = NowNs();
        err = config->backend->Infer(&result, *ctx->options, ctx->inputs,
                                     ctx->outputs);
        uint64_t end = NowNs();
        if (err.IsOk() && result != nullptr) {
          err = result->RequestStatus();
        }
        delete result;
        if (err.IsOk()) {
          RecordRequest(stat.get(), start, end, ctx->options->sequence_end,
                        false);
        }
      }
      if (!err.IsOk()) {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
      continue;
    }

    // streaming: requests ride ONE bidi stream per worker; the stream
    // callback multiplexes completions back to their contexts by request
    // id (reference --streaming, main.cc:610-748). Mid-stream responses
    // of decoupled models are counted only at the final response.
    if (options_.streaming && !config->stream_started) {
      ThreadStat* stat_ptr = stat.get();
      ThreadConfig* cfg = config.get();
      Error serr = config->backend->StartStream(
          [this, cfg, stat_ptr, ongoing](tpuclient::InferResult* result) {
            uint64_t end = NowNs();
            Error status = result != nullptr ? result->RequestStatus()
                                             : Error("null stream response");
            bool final = IsFinalStreamResponse(result);
            std::string id;
            if (result != nullptr) result->Id(&id);
            delete result;
            if (!final) return;
            StreamPending pending;
            bool found = false;
            {
              std::lock_guard<std::mutex> lk(cfg->stream_mu);
              auto it = cfg->stream_pending.find(id);
              if (it != cfg->stream_pending.end()) {
                pending = it->second;
                cfg->stream_pending.erase(it);
                found = true;
              }
            }
            if (!found) {
              if (!status.IsOk()) {
                // Terminal stream failure (reset/disconnect): the dead
                // stream will deliver no more callbacks, so every request
                // still pending on it must be failed out here or the
                // end-of-run drain (ongoing > 0) never terminates.
                std::vector<StreamPending> orphans;
                {
                  std::lock_guard<std::mutex> lk(cfg->stream_mu);
                  for (auto& kv : cfg->stream_pending)
                    orphans.push_back(kv.second);
                  cfg->stream_pending.clear();
                }
                if (!orphans.empty()) {
                  {
                    std::lock_guard<std::mutex> lk(stat_ptr->mu);
                    stat_ptr->status = status;
                  }
                  for (auto& o : orphans) o.ctx->inflight = false;
                  ongoing->fetch_sub(orphans.size());
                  wake_cv_.notify_all();
                }
              }
              return;  // late/unknown id (stream already failed)
            }
            if (status.IsOk()) {
              RecordRequest(stat_ptr, pending.start_ns, end, pending.seq_end,
                            false);
            } else {
              std::lock_guard<std::mutex> lk(stat_ptr->mu);
              stat_ptr->status = status;
            }
            pending.ctx->inflight = false;
            ongoing->fetch_sub(1);
            wake_cv_.notify_all();
          });
      if (!serr.IsOk()) {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = serr;
        return;
      }
      config->stream_started = true;
    }

    // async: top up in-flight requests to the target share
    while (ongoing->load() < target && !exit_.load()) {
      // find or create a free context
      InferContext* ctx = nullptr;
      for (auto& c : config->ctxs) {
        if (!c->inflight) {
          ctx = c.get();
          break;
        }
      }
      if (ctx == nullptr) {
        Error err = MakeContext(config.get(), &ctx);
        if (!err.IsOk()) {
          std::lock_guard<std::mutex> lk(stat->mu);
          stat->status = err;
          return;
        }
      }
      Error err = PrepareRequest(ctx);
      if (!err.IsOk()) {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
      ctx->inflight = true;
      ctx->start_ns = NowNs();
      bool seq_end = ctx->options->sequence_end;
      ThreadStat* stat_ptr = stat.get();
      if (options_.streaming) {
        // Unique id for completion routing (the stream callback is shared
        // by every context on this worker).
        std::string rid =
            std::to_string(config->index) + "-" +
            std::to_string(config->stream_seq.fetch_add(1));
        ctx->options->request_id = rid;
        {
          std::lock_guard<std::mutex> lk(config->stream_mu);
          config->stream_pending[rid] = {ctx, ctx->start_ns, seq_end};
        }
        ongoing->fetch_add(1);
        err = config->backend->AsyncStreamInfer(*ctx->options, ctx->inputs,
                                                ctx->outputs);
        if (!err.IsOk()) {
          {
            std::lock_guard<std::mutex> lk(config->stream_mu);
            config->stream_pending.erase(rid);
          }
          ctx->inflight = false;
          ongoing->fetch_sub(1);
          std::lock_guard<std::mutex> sk(stat->mu);
          stat->status = err;
          return;
        }
        continue;
      }
      // count before dispatch: the callback may fire (and decrement) before
      // AsyncInfer returns
      ongoing->fetch_add(1);
      err = config->backend->AsyncInfer(
          [this, ctx, ongoing, stat_ptr, seq_end](
              tpuclient::InferResult* result) {
            uint64_t end = NowNs();
            Error status =
                result != nullptr ? result->RequestStatus() : Error("null");
            delete result;
            if (status.IsOk()) {
              RecordRequest(stat_ptr, ctx->start_ns, end, seq_end, false);
            } else {
              std::lock_guard<std::mutex> lk(stat_ptr->mu);
              stat_ptr->status = status;
            }
            ctx->inflight = false;
            ongoing->fetch_sub(1);
            wake_cv_.notify_all();
          },
          *ctx->options, ctx->inputs, ctx->outputs);
      if (!err.IsOk()) {
        ctx->inflight = false;
        ongoing->fetch_sub(1);
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
    }
    // wait for a completion or a concurrency change
    std::unique_lock<std::mutex> lk(wake_mutex_);
    wake_cv_.wait_for(lk, std::chrono::milliseconds(50), [&]() {
      return exit_.load() || ongoing->load() < share->target.load();
    });
  }
  // drain in-flight requests before the backend is destroyed
  while (ongoing->load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  if (options_.streaming && config->stream_started) {
    config->backend->StopStream();
    config->stream_started = false;
  }
}

}  // namespace tpuperf
