#include "load_manager.h"

#include <unistd.h>

#include <algorithm>
#include <cstring>

#include "tpuclient/shm_utils.h"

using tpuclient::Error;
using tpuclient::InferInput;
using tpuclient::InferOptions;
using tpuclient::InferRequestedOutput;

namespace tpuperf {

LoadManager::LoadManager(const LoadOptions& options,
                         ClientBackendFactory factory,
                         std::shared_ptr<ModelParser> parser,
                         std::shared_ptr<DataLoader> data_loader)
    : options_(options), factory_(std::move(factory)),
      parser_(std::move(parser)), data_loader_(std::move(data_loader)) {
  is_sequence_ =
      parser_->Scheduler() == ModelParser::SchedulerType::SEQUENCE ||
      parser_->Scheduler() == ModelParser::SchedulerType::ENSEMBLE_SEQUENCE;
  next_seq_id_ = options_.start_sequence_id;
}

LoadManager::~LoadManager() {
  StopWorkerThreads();
  ClientBackend* shm_backend = nullptr;
  if (!thread_configs_.empty() && thread_configs_[0]->backend != nullptr) {
    shm_backend = thread_configs_[0]->backend.get();
  } else if (warmup_config_ != nullptr &&
             warmup_config_->backend != nullptr) {
    shm_backend = warmup_config_->backend.get();
  }
  if (shm_ready_ && shm_backend != nullptr) {
    CleanupSharedMemory(shm_backend);
  }
  if (warmup_config_ != nullptr) thread_configs_.push_back(warmup_config_);
  for (auto& ctx_cfg : thread_configs_) {
    for (auto& ctx : ctx_cfg->ctxs) {
      for (auto* input : ctx->inputs) delete input;
      for (const auto* output : ctx->outputs) delete output;
    }
  }
}

// Serialized host-staged TPU region handle — must match the server's
// make_tpu_handle schema (client_tpu/engine/shm.py): the TPU analog of the
// reference's cudaIpcMemHandle_t byte transport (grpc_client.cc:811).
std::string LoadManager::MakeTpuHandle(const std::string& key,
                                       size_t byte_size, int device_id) {
  return std::string("{\"kind\": \"host_staged\", \"key\": \"") + key +
         "\", \"byte_size\": " + std::to_string(byte_size) +
         ", \"device_id\": " + std::to_string(device_id) + "}";
}

Error LoadManager::RegisterShmRegion(ClientBackend* backend,
                                     const ShmRegion& region) {
  if (options_.shm_type == SharedMemoryType::TPU) {
    return backend->RegisterTpuSharedMemory(
        region.name, MakeTpuHandle(region.key, region.byte_size, 0),
        /*device_id=*/0, region.byte_size);
  }
  return backend->RegisterSystemSharedMemory(region.name, region.key,
                                             region.byte_size);
}

std::string LoadManager::ShmRegionName(const std::string& input, size_t stream,
                                       size_t step) const {
  return "perf_" + input + "_" + std::to_string(stream) + "_" +
         std::to_string(step);
}

Error LoadManager::InitSharedMemory(ClientBackend* backend) {
  // One region per input x stream x step holding the wire bytes, plus one
  // region per output (reference load_manager.cc:256-446). Regions are
  // registered with the server by /dev/shm key.
  for (size_t stream = 0; stream < data_loader_->StreamCount(); ++stream) {
    for (size_t step = 0; step < data_loader_->StepCount(stream); ++step) {
      for (const auto& kv : parser_->Inputs()) {
        const uint8_t* data = nullptr;
        size_t byte_size = 0;
        Error err = data_loader_->GetInputData(kv.first, stream, step, &data,
                                               &byte_size, nullptr);
        if (!err.IsOk()) return err;
        // batch>1 repeats the step data per batched sample
        size_t region_size = byte_size * options_.batch_size;

        ShmRegion region;
        region.name = ShmRegionName(kv.first, stream, step);
        region.key = "/" + region.name;
        region.byte_size = region_size;
        err = tpuclient::CreateSharedMemoryRegion(region.key, region_size,
                                                  &region.fd);
        if (!err.IsOk()) return err;
        err = tpuclient::MapSharedMemory(region.fd, 0, region_size,
                                         &region.base);
        if (!err.IsOk()) return err;
        for (int32_t b = 0; b < options_.batch_size; ++b) {
          memcpy(static_cast<uint8_t*>(region.base) + b * byte_size, data,
                 byte_size);
        }
        err = RegisterShmRegion(backend, region);
        if (!err.IsOk()) return err;
        shm_regions_.push_back(region);
      }
    }
  }
  for (const auto& kv : parser_->Outputs()) {
    ShmRegion region;
    region.name = "perf_out_" + kv.first;
    region.key = "/" + region.name;
    region.byte_size = options_.output_shm_size;
    Error err = tpuclient::CreateSharedMemoryRegion(
        region.key, region.byte_size, &region.fd);
    if (!err.IsOk()) return err;
    err = tpuclient::MapSharedMemory(region.fd, 0, region.byte_size,
                                     &region.base);
    if (!err.IsOk()) return err;
    err = RegisterShmRegion(backend, region);
    if (!err.IsOk()) return err;
    shm_regions_.push_back(region);
  }
  shm_ready_ = true;
  return Error::Success();
}

void LoadManager::CleanupSharedMemory(ClientBackend* backend) {
  for (auto& region : shm_regions_) {
    if (options_.shm_type == SharedMemoryType::TPU)
      backend->UnregisterTpuSharedMemory(region.name);
    else
      backend->UnregisterSystemSharedMemory(region.name);
    if (region.base != nullptr)
      tpuclient::UnmapSharedMemory(region.base, region.byte_size);
    if (region.fd >= 0) tpuclient::CloseSharedMemory(region.fd);
    tpuclient::UnlinkSharedMemoryRegion(region.key);
  }
  shm_regions_.clear();
  shm_ready_ = false;
}

Error LoadManager::WarmUp(size_t n) {
  if (n == 0) return Error::Success();
  if (is_sequence_) {
    // A warmup request would open a server-side sequence slot
    // (sequence_start without ever reaching sequence_end) that then sits
    // orphaned through the measurement run. Sequence models warm through
    // the stability search instead.
    fprintf(stderr,
            "warning: --warmup-request-count ignored for sequence-scheduled "
            "models (a warmup sequence would be left open server-side)\n");
    return Error::Success();
  }
  warmup_config_ = std::make_shared<ThreadConfig>();
  warmup_config_->index = 0;
  Error err = factory_.Create(&warmup_config_->backend);
  if (!err.IsOk()) return err;
  // Same once-only shm setup the worker paths use (regions stay
  // registered for the measurement phase; the destructor cleans up).
  if (options_.shm_type != SharedMemoryType::NONE && !shm_ready_) {
    err = InitSharedMemory(warmup_config_->backend.get());
    if (!err.IsOk()) return err;
  }
  InferContext* ctx = nullptr;
  err = MakeContext(warmup_config_.get(), &ctx);
  if (!err.IsOk()) return err;
  for (size_t i = 0; i < n && err.IsOk(); ++i) {
    err = PrepareRequest(ctx);
    if (!err.IsOk()) break;
    tpuclient::InferResult* result = nullptr;
    err = warmup_config_->backend->Infer(&result, *ctx->options, ctx->inputs,
                                         ctx->outputs);
    if (err.IsOk() && result != nullptr) {
      // HTTP-kind failures ride the result, not the call status.
      err = result->RequestStatus();
    }
    delete result;
  }
  return err;
}

Error LoadManager::MakeContext(ThreadConfig* config, InferContext** out) {
  auto ctx = std::make_unique<InferContext>();
  ctx->options = std::make_unique<InferOptions>(parser_->Name());
  ctx->options->model_version = parser_->Version();
  ctx->options->client_timeout_us = options_.request_timeout_us;
  ctx->options->compression_algorithm = options_.compression;
  ctx->stream = config->index % std::max<size_t>(1, data_loader_->StreamCount());

  bool batched = parser_->MaxBatchSize() > 0;
  for (const auto& kv : parser_->Inputs()) {
    const uint8_t* data = nullptr;
    size_t byte_size = 0;
    std::vector<int64_t> shape;
    Error err = data_loader_->GetInputData(kv.first, ctx->stream, 0, &data,
                                           &byte_size, &shape);
    if (!err.IsOk()) return err;
    std::vector<int64_t> full_shape;
    if (batched) full_shape.push_back(options_.batch_size);
    full_shape.insert(full_shape.end(), shape.begin(), shape.end());

    InferInput* input = nullptr;
    err = InferInput::Create(&input, kv.first, full_shape, kv.second.datatype);
    if (!err.IsOk()) return err;
    ctx->inputs.push_back(input);
  }
  for (const auto& kv : parser_->Outputs()) {
    InferRequestedOutput* output = nullptr;
    Error err = InferRequestedOutput::Create(&output, kv.first);
    if (!err.IsOk()) return err;
    if (options_.shm_type != SharedMemoryType::NONE) {
      output->SetSharedMemory("perf_out_" + kv.first,
                              options_.output_shm_size);
    }
    ctx->outputs.push_back(output);
  }
  config->ctxs.push_back(std::move(ctx));
  *out = config->ctxs.back().get();
  return Error::Success();
}

Error LoadManager::PrepareRequest(InferContext* ctx) {
  // sequence bookkeeping first: it picks the data step within the stream
  if (is_sequence_) {
    if (ctx->seq_remaining == 0) {
      std::lock_guard<std::mutex> lk(seq_mutex_);
      ctx->seq_id = next_seq_id_++;
      // length jitter: 80%..120% of the nominal sequence length
      uint64_t len = options_.sequence_length;
      uint64_t lo = std::max<uint64_t>(1, len * 4 / 5);
      uint64_t hi = std::max<uint64_t>(lo, len * 6 / 5);
      ctx->seq_remaining = lo + seq_len_gen_() % (hi - lo + 1);
      ctx->options->sequence_start = true;
      ctx->step = 0;
    } else {
      ctx->options->sequence_start = false;
    }
    ctx->options->sequence_id = ctx->seq_id;
    ctx->seq_remaining--;
    ctx->options->sequence_end = (ctx->seq_remaining == 0);
  }

  size_t steps = data_loader_->StepCount(ctx->stream);
  size_t step = steps > 0 ? ctx->step % steps : 0;

  for (auto* input : ctx->inputs) {
    if (options_.shm_type != SharedMemoryType::NONE) {
      const uint8_t* data = nullptr;
      size_t byte_size = 0;
      Error err = data_loader_->GetInputData(input->Name(), ctx->stream, step,
                                             &data, &byte_size, nullptr);
      if (!err.IsOk()) return err;
      input->SetSharedMemory(ShmRegionName(input->Name(), ctx->stream, step),
                             byte_size * options_.batch_size);
      continue;
    }
    const uint8_t* data = nullptr;
    size_t byte_size = 0;
    Error err = data_loader_->GetInputData(input->Name(), ctx->stream, step,
                                           &data, &byte_size, nullptr);
    if (!err.IsOk()) return err;
    input->Reset();
    for (int32_t b = 0; b < options_.batch_size; ++b) {
      err = input->AppendRaw(data, byte_size);
      if (!err.IsOk()) return err;
    }
  }
  ctx->step++;
  return Error::Success();
}

void LoadManager::RecordRequest(ThreadStat* stat, uint64_t start_ns,
                                uint64_t end_ns, bool sequence_end,
                                bool delayed) {
  std::lock_guard<std::mutex> lk(stat->mu);
  stat->requests.push_back({start_ns, end_ns, sequence_end, delayed});
}

Error LoadManager::CheckHealth() {
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lk(stat->mu);
    if (!stat->status.IsOk()) return stat->status;
  }
  return Error::Success();
}

Error LoadManager::SwapTimestamps(TimestampVector* out) {
  out->clear();
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lk(stat->mu);
    out->insert(out->end(), stat->requests.begin(), stat->requests.end());
    stat->requests.clear();
  }
  return Error::Success();
}

size_t LoadManager::CountCollectedRequests() {
  size_t n = 0;
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lk(stat->mu);
    n += stat->requests.size();
  }
  return n;
}

Error LoadManager::GetAccumulatedClientStat(tpuclient::InferStat* stat) {
  *stat = tpuclient::InferStat();
  for (auto& config : thread_configs_) {
    if (config->backend == nullptr) continue;
    tpuclient::InferStat s;
    Error err = config->backend->ClientInferStat(&s);
    if (!err.IsOk()) return err;
    stat->completed_request_count += s.completed_request_count;
    stat->cumulative_total_request_time_ns +=
        s.cumulative_total_request_time_ns;
    stat->cumulative_send_time_ns += s.cumulative_send_time_ns;
    stat->cumulative_receive_time_ns += s.cumulative_receive_time_ns;
  }
  return Error::Success();
}

void LoadManager::StopWorkerThreads() {
  exit_.store(true);
  for (auto& t : threads_) {
    if (t.joinable()) t.join();
  }
  threads_.clear();
}

}  // namespace tpuperf
