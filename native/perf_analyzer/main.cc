// tpu_perf_analyzer — load generator / latency profiler CLI.
//
// Counterpart of the reference's perf_analyzer main
// (/root/reference/src/c++/perf_analyzer/main.cc:645-1668): option parsing,
// manager/profiler wiring, human summary and CSV export. Backend kinds:
// http (default, our native client), capi (in-process engine, when built).
#include <getopt.h>
#include <signal.h>
#include <sys/stat.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <mutex>
#include <set>
#include <sstream>
#include <vector>

#include "inference_profiler.h"

using tpuclient::Error;
using namespace tpuperf;

namespace {

void SignalHandler(int) { EarlyExit().store(true); }

void Usage(const char* msg = nullptr) {
  if (msg != nullptr) fprintf(stderr, "error: %s\n", msg);
  fprintf(stderr, R"(Usage: tpu_perf_analyzer -m <model> [options]

Options:
  -m <name>              model name (required)
  -x <version>           model version
  -u <url>               server url (default localhost:8000 http,
                         localhost:8001 grpc)
  -i <protocol>          protocol: http (default) | grpc
  -b <n>                 batch size (default 1)
  -a                     async mode
  --concurrency-range <start:end:step>
  --request-rate-range <start:end:step>
  --request-distribution <poisson|constant> (default constant)
  --request-intervals <file>   custom inter-request intervals (ns, one/line)
  --binary-search        binary instead of linear search
  -p <ms>                measurement window (default 5000)
  --measurement-mode <time_windows|count_windows>
  --measurement-request-count <n>   (count mode window, default 50)
  -s <pct>               stability threshold percent (default 10)
  -r <n>                 max trials per step (default 10)
  -l <us>                latency threshold; search stops above it
  --percentile <n>       use p<n> latency for stability (default: average)
  --input-data <zero|random|path.json|dir>  (default random; a directory
                reads raw bytes from <dir>/<input name>, text lines for BYTES)
  --shape <name:d1,d2,...>    concrete shape for dynamic input dims
  --string-length <n>    BYTES element length (default 16)
  --string-data <s>      fixed BYTES element value
  --sequence-length <n>  requests per sequence (default 20)
  --start-sequence-id <n>
  --num-of-sequences <n> distinct concurrent sequences under request-rate
                         or custom-interval load (default 4; concurrency
                         mode sizes the pool by the concurrency level)
  --grpc-compression-algorithm <none|gzip|deflate>  per-call message
                         compression on gRPC requests (default none)
  --model-signature-name <s>  TFS PredictionService signature
                         (tfserving kind; default serving_default)
  --shared-memory <none|system|tpu>   tensor transport (default none)
  --output-shared-memory-size <bytes>
  --max-threads <n>      worker thread cap (default 16)
  --warmup-request-count <n>  unmeasured requests before profiling (lets
                         the server compile per-bucket executables outside
                         the measurement windows; default 0)
  --streaming            drive requests over one bidi gRPC stream per
                         worker (implies -a and tpu_grpc; sequence steps
                         keep per-context order)
  --generative           token-streaming profile against a decoupled
                         model: tok/s + TTFT / inter-token-latency
                         percentiles through the gRPC stream (implies
                         --streaming; streams = --concurrency-range start)
  --generative-max-tokens <n>  tokens per generation stream (default 32)
  --generative-no-coalesce     disable server-side token coalescing
                         (per-message tax A/B; default requests coalescing)
  --service-kind <tpu_http|tpu_grpc|tpu_capi|tfserving|torchserve>
                         endpoint kind (default
                         tpu_http; -i grpc implies tpu_grpc);
                         tpu_capi runs the engine in-process via
                         libtpuserver.so — no network, sync only
  --capi-library-path <path>   libtpuserver.so location
                               (default ./build/libtpuserver.so)
  --capi-models <csv>    model-zoo models the in-process server hosts
                         (default: the -m model)
  --capi-repo-root <dir> repo root for the embedded python (default .)
  -f <path>              export CSV
  -v                     verbose
)");
  exit(msg != nullptr ? 1 : 0);
}

struct Args {
  std::string model;
  std::string version;
  std::string url = "localhost:8000";
  bool url_set = false;
  std::string protocol = "http";
  int batch_size = 1;
  bool async = false;
  bool has_concurrency = false;
  size_t conc_start = 1, conc_end = 1, conc_step = 1;
  bool has_rate = false;
  double rate_start = 0, rate_end = 0, rate_step = 1;
  std::string intervals_file;
  bool binary_search = false;
  uint64_t window_ms = 5000;
  MeasurementMode mode = MeasurementMode::TIME_WINDOWS;
  uint64_t request_count = 50;
  double stability_pct = 10.0;
  size_t max_trials = 10;
  uint64_t latency_threshold_us = 0;
  int64_t percentile = -1;
  std::string input_data = "random";
  DataLoader::Options data_opts;
  uint64_t sequence_length = 20;
  uint64_t start_sequence_id = 1;
  size_t num_of_sequences = 4;
  tpuclient::GrpcCompression compression = tpuclient::GrpcCompression::NONE;
  std::string signature_name;  // --model-signature-name (TFS kind)
  SharedMemoryType shm = SharedMemoryType::NONE;
  size_t output_shm_size = 100 * 1024;
  size_t max_threads = 16;
  std::string csv_path;
  bool verbose = false;
  bool poisson = false;
  BackendKind kind = BackendKind::TPU_HTTP;
  std::string capi_lib = "./build/libtpuserver.so";
  std::string capi_models;
  std::string capi_repo_root = ".";
  size_t warmup_requests = 0;
  // --streaming: drive requests over the bidi gRPC stream (reference
  // main.cc:610-748); --generative additionally measures token streaming
  // (TTFT / inter-token latency / tok/s) against a decoupled model.
  bool streaming = false;
  bool generative = false;
  uint64_t gen_max_tokens = 32;
  // Server-side token coalescing (one message may carry k tokens). On by
  // default: it is the production posture; --generative-no-coalesce
  // measures the per-message tax A/B.
  bool gen_coalesce = true;
};

bool ParseRange(const char* s, double* a, double* b, double* c) {
  return sscanf(s, "%lf:%lf:%lf", a, b, c) >= 2;
}

void PrintServerStats(const char* indent, const ServerSideStats& s) {
  uint64_t n = std::max<uint64_t>(1, s.success_count);
  printf("%sInference count: %lu\n", indent,
         static_cast<unsigned long>(s.inference_count));
  printf("%sExecution count: %lu\n", indent,
         static_cast<unsigned long>(s.execution_count));
  printf("%sAvg queue: %.0f usec, compute input: %.0f usec, "
         "compute infer: %.0f usec, compute output: %.0f usec\n",
         indent, s.queue_time_ns / 1e3 / n, s.compute_input_time_ns / 1e3 / n,
         s.compute_infer_time_ns / 1e3 / n,
         s.compute_output_time_ns / 1e3 / n);
}

void PrintStatus(const PerfStatus& st) {
  if (st.concurrency > 0)
    printf("Concurrency: %zu\n", st.concurrency);
  else
    printf("Request rate: %.1f infer/sec\n", st.request_rate);
  const auto& c = st.client_stats;
  printf("  Client:\n");
  printf("    Request count: %lu\n", static_cast<unsigned long>(c.request_count));
  printf("    Throughput: %.1f infer/sec\n", c.infer_per_sec);
  if (st.on_sequence_model)
    printf("    Sequence throughput: %.1f seq/sec\n", c.sequence_per_sec);
  if (c.delayed_request_count > 0)
    printf("    Delayed requests: %zu\n", c.delayed_request_count);
  printf("    Avg latency: %.0f usec (std %.0f usec)\n", c.avg_latency_ns / 1e3,
         c.std_latency_ns / 1e3);
  for (auto& kv : c.percentile_latency_ns) {
    printf("    p%zu latency: %.0f usec\n", kv.first, kv.second / 1e3);
  }
  printf("    Avg HTTP send/recv: %.0f / %.0f usec\n", c.avg_send_time_ns / 1e3,
         c.avg_receive_time_ns / 1e3);
  printf("  Server:\n");
  PrintServerStats("    ", st.server_stats);
  for (auto& kv : st.server_stats.composing) {
    printf("    Composing model %s:\n", kv.first.c_str());
    PrintServerStats("      ", kv.second);
  }
}

void WriteCsv(const Args& args, const std::vector<PerfStatus>& results) {
  std::ofstream f(args.csv_path);
  if (!f.good()) {
    fprintf(stderr, "cannot write CSV to %s\n", args.csv_path.c_str());
    return;
  }
  f << "Concurrency,Request Rate,Inferences/Second,Client Send,"
    << "Network+Server Send/Recv,Server Queue,Server Compute Input,"
    << "Server Compute Infer,Server Compute Output,Client Recv,"
    << "p50 latency,p90 latency,p95 latency,p99 latency,Avg latency\n";
  for (const auto& st : results) {
    const auto& c = st.client_stats;
    const auto& s = st.server_stats;
    uint64_t n = std::max<uint64_t>(1, s.success_count);
    uint64_t queue_us = s.queue_time_ns / 1000 / n;
    uint64_t ci_us = s.compute_input_time_ns / 1000 / n;
    uint64_t cf_us = s.compute_infer_time_ns / 1000 / n;
    uint64_t co_us = s.compute_output_time_ns / 1000 / n;
    uint64_t send_us = c.avg_send_time_ns / 1000;
    uint64_t recv_us = c.avg_receive_time_ns / 1000;
    // Network+Server Send/Recv = client latency - client send/recv -
    // server phases, clamped at 0 (reference main.cc:1576-1590)
    int64_t net = static_cast<int64_t>(c.avg_latency_ns / 1000) - send_us -
                  recv_us - queue_us - ci_us - cf_us - co_us;
    if (net < 0) net = 0;
    auto pct = [&](size_t p) -> uint64_t {
      auto it = c.percentile_latency_ns.find(p);
      return it == c.percentile_latency_ns.end() ? 0 : it->second / 1000;
    };
    f << st.concurrency << "," << st.request_rate << "," << c.infer_per_sec
      << "," << send_us << "," << net << "," << queue_us << "," << ci_us
      << "," << cf_us << "," << co_us << "," << recv_us << "," << pct(50)
      << "," << pct(90) << "," << pct(95) << "," << pct(99) << ","
      << c.avg_latency_ns / 1000 << "\n";
  }
  printf("CSV written to %s\n", args.csv_path.c_str());

  // Ensembles additionally get one CSV per composing model with the
  // server-side phase breakdown (the reference writes `<path>.<model>`
  // files for composing models, main.cc:1503-1668).
  std::set<std::string> composing_names;
  for (const auto& st : results)
    for (const auto& kv : st.server_stats.composing)
      composing_names.insert(kv.first);
  for (const auto& name : composing_names) {
    std::string path = args.csv_path + "." + name;
    std::ofstream cf(path);
    if (!cf.good()) {
      fprintf(stderr, "cannot write CSV to %s\n", path.c_str());
      continue;
    }
    cf << "Concurrency,Request Rate,Inference Count,Execution Count,"
       << "Server Queue,Server Compute Input,Server Compute Infer,"
       << "Server Compute Output\n";
    for (const auto& st : results) {
      auto it = st.server_stats.composing.find(name);
      if (it == st.server_stats.composing.end()) continue;
      const auto& s = it->second;
      uint64_t n = std::max<uint64_t>(1, s.success_count);
      cf << st.concurrency << "," << st.request_rate << ","
         << s.success_count << "," << s.execution_count << ","
         << s.queue_time_ns / 1000 / n << ","
         << s.compute_input_time_ns / 1000 / n << ","
         << s.compute_infer_time_ns / 1000 / n << ","
         << s.compute_output_time_ns / 1000 / n << "\n";
    }
    printf("CSV written to %s\n", path.c_str());
  }
}


// ---------------------------------------------------------------------------
// Generative (token-streaming) profile: N concurrent generation streams over
// ONE bidi gRPC stream, measuring time-to-first-token, inter-token latency,
// and aggregate tok/s through the networked stack. The reference profiler
// has no token vocabulary (its decoupled mode just counts responses); a
// token-serving framework must own these numbers end to end.
// ---------------------------------------------------------------------------

uint64_t Pct(std::vector<uint64_t>& v, double q) {
  if (v.empty()) return 0;
  std::sort(v.begin(), v.end());
  size_t i = std::min(v.size() - 1, size_t(double(v.size()) * q));
  return v[i];
}

int RunGenerativeProfile(const ClientBackendFactory& factory,
                         const ModelParser& parser, const Args& args) {
  if (!parser.IsDecoupled()) {
    fprintf(stderr,
            "--generative requires a decoupled (token-streaming) model; "
            "'%s' is not decoupled\n", parser.Name().c_str());
    return 1;
  }
  // The prompt tensor: first INT32 input with a dynamic last dim
  // (tiny_gpt: INPUT_IDS INT32 [-1]).
  std::string input_name;
  for (const auto& kv : parser.Inputs()) {
    if (kv.second.datatype == "INT32") { input_name = kv.first; break; }
  }
  if (input_name.empty()) {
    fprintf(stderr, "--generative: model has no INT32 prompt input\n");
    return 1;
  }
  size_t streams = args.has_concurrency ? std::max<size_t>(1, args.conc_start)
                                        : 8;

  std::unique_ptr<ClientBackend> backend;
  Error err = factory.Create(&backend);
  if (!err.IsOk()) {
    fprintf(stderr, "backend: %s\n", err.Message().c_str());
    return 1;
  }

  struct Slot {
    std::atomic<bool> busy{false};
    uint64_t start_ns = 0;
    uint64_t last_ns = 0;
    bool first_seen = false;
  };
  std::vector<Slot> slots(streams);
  std::mutex mu;
  std::condition_variable cv;
  std::vector<uint64_t> ttft_ns, itl_ns;
  uint64_t tokens = 0, messages = 0, completed = 0, errors = 0;
  std::string first_error;

  err = backend->StartStream([&](tpuclient::InferResult* result) {
    uint64_t now = NowNs();
    Error status = result != nullptr ? result->RequestStatus()
                                     : Error("null stream response");
    bool final = IsFinalStreamResponse(result);
    std::string id;
    uint64_t n_tok = 1;
    if (result != nullptr) {
      result->Id(&id);
      // Coalesced responses carry k tokens in one message (the server
      // merges a backlogged stream's rows); count by payload element
      // count, not by message count.
      const uint8_t* buf = nullptr;
      size_t nbytes = 0;
      if (result->RawData("TOKEN", &buf, &nbytes).IsOk() &&
          nbytes >= sizeof(int32_t)) {
        n_tok = nbytes / sizeof(int32_t);
      }
    }
    delete result;
    if (!status.IsOk()) {
      // Error results may carry no request id (the stream-level failure
      // path builds them without a response proto), so attribution to a
      // slot is unreliable — and any error aborts the profile anyway.
      // Release every slot so the drain completes promptly.
      std::lock_guard<std::mutex> lk(mu);
      ++errors;
      if (first_error.empty()) first_error = status.Message();
      for (auto& sl : slots) sl.busy.store(false);
      cv.notify_all();
      return;
    }
    if (id.empty()) return;
    size_t idx = strtoull(id.c_str(), nullptr, 10);
    if (idx >= slots.size()) return;
    Slot& sl = slots[idx];
    std::lock_guard<std::mutex> lk(mu);
    if (final) {
      ++completed;
      sl.busy.store(false);
      cv.notify_all();
      return;
    }
    tokens += n_tok;
    ++messages;
    if (!sl.first_seen) {
      sl.first_seen = true;
      ttft_ns.push_back(now - sl.start_ns);
      // tokens beyond the first in the same message have no observable
      // intra-message spacing; they contribute no TTFT/ITL samples
    } else {
      // Per-token ITL: a k-token message closes k token intervals spanning
      // one observed gap; record gap/k once per token so percentiles stay
      // token-weighted under coalescing.
      uint64_t per = (now - sl.last_ns) / n_tok;
      for (uint64_t i = 0; i < n_tok; ++i) itl_ns.push_back(per);
    }
    sl.last_ns = now;
  });
  if (!err.IsOk()) {
    fprintf(stderr, "StartStream: %s\n", err.Message().c_str());
    return 1;
  }
  // Every exit below this point must stop the stream BEFORE the locals the
  // reader callback captures by reference (slots/mu/cv/counters) are
  // destroyed: an early `return 1` (e.g. warmup failure after a server-side
  // cancel) used to leave the reader thread delivering into freed stack
  // frames — observed as a SIGSEGV in the round-5 gen_net capture.
  struct StreamGuard {
    ClientBackend* b;
    ~StreamGuard() {
      if (b != nullptr) b->StopStream();
    }
  } stream_guard{backend.get()};

  // Prompt length honors --shape <input>:N (the same CLI surface the
  // load-manager path consumes); default 4 tokens.
  size_t prompt_len = 4;
  auto shape_it = args.data_opts.shapes.find(input_name);
  if (shape_it != args.data_opts.shapes.end()) {
    int64_t n = 1;
    for (int64_t d : shape_it->second) n *= d;
    if (n > 0) prompt_len = size_t(n);
  }
  std::vector<int32_t> prompt(prompt_len);
  for (size_t i = 0; i < prompt_len; ++i) prompt[i] = 1 + int32_t(i % 100);
  tpuclient::InferInput* raw_in = nullptr;
  err = tpuclient::InferInput::Create(
      &raw_in, input_name, {int64_t(prompt.size())}, "INT32");
  if (!err.IsOk()) {
    fprintf(stderr, "input: %s\n", err.Message().c_str());
    return 1;
  }
  std::unique_ptr<tpuclient::InferInput> input(raw_in);
  input->AppendRaw(reinterpret_cast<const uint8_t*>(prompt.data()),
                   prompt.size() * sizeof(int32_t));

  auto dispatch = [&](size_t idx) -> Error {
    Slot& sl = slots[idx];
    sl.first_seen = false;
    sl.start_ns = NowNs();
    sl.last_ns = sl.start_ns;
    sl.busy.store(true);
    tpuclient::InferOptions options(args.model);
    options.model_version = args.version;
    options.request_id = std::to_string(idx);
    options.int_parameters["max_tokens"] = int64_t(args.gen_max_tokens);
    // Let the server merge backlogged tokens for this stream into one
    // message ([k]-shaped TOKEN); the callback above counts by element.
    if (args.gen_coalesce) {
      options.bool_parameters["response_coalesce"] = true;
    }
    return backend->AsyncStreamInfer(options, {input.get()}, {});
  };

  auto run_phase = [&](uint64_t duration_ms) -> Error {
    uint64_t deadline = NowNs() + duration_ms * 1000000ull;
    while (NowNs() < deadline) {
      for (size_t i = 0; i < streams; ++i) {
        if (!slots[i].busy.load()) {
          Error derr = dispatch(i);
          if (!derr.IsOk()) return derr;
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv.wait_for(lk, std::chrono::milliseconds(20));
      if (!first_error.empty()) return Error(first_error);
    }
    // drain: no redispatch, wait for in-flight streams to finish
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(60), [&] {
      for (const auto& sl : slots)
        if (sl.busy.load()) return false;
      return true;
    });
    return Error::Success();
  };

  // Warmup (compiles server-side executables; discarded), then the window.
  err = run_phase(std::max<uint64_t>(args.window_ms / 2, 1000));
  if (!err.IsOk()) {
    fprintf(stderr, "generative warmup failed: %s\n", err.Message().c_str());
    return 1;
  }
  {
    std::lock_guard<std::mutex> lk(mu);
    ttft_ns.clear();
    itl_ns.clear();
    tokens = 0;
    messages = 0;
    completed = 0;
  }
  uint64_t t0 = NowNs();
  err = run_phase(args.window_ms);
  uint64_t elapsed_ns = NowNs() - t0;
  if (!err.IsOk()) {
    fprintf(stderr, "generative profile failed: %s\n",
            err.Message().c_str());
    return 1;
  }
  backend->StopStream();
  stream_guard.b = nullptr;  // stopped explicitly; guard must not re-stop

  std::vector<uint64_t> ttft, itl;
  uint64_t n_tokens, n_messages, n_completed;
  {
    std::lock_guard<std::mutex> lk(mu);
    ttft = ttft_ns;
    itl = itl_ns;
    n_tokens = tokens;
    n_messages = messages;
    n_completed = completed;
  }
  double secs = double(elapsed_ns) / 1e9;
  double tok_s = secs > 0 ? double(n_tokens) / secs : 0;
  printf("Generative stream profile: model=%s, streams=%zu, "
         "max_tokens=%lu, window %.1fs\n",
         args.model.c_str(), streams,
         static_cast<unsigned long>(args.gen_max_tokens), secs);
  printf("  Completed streams: %lu, tokens: %lu, tok/s: %.1f, "
         "tokens/message: %.2f\n",
         static_cast<unsigned long>(n_completed),
         static_cast<unsigned long>(n_tokens), tok_s,
         n_messages > 0 ? double(n_tokens) / double(n_messages) : 0.0);
  printf("  TTFT usec: p50 %lu, p90 %lu, p99 %lu\n",
         static_cast<unsigned long>(Pct(ttft, 0.50) / 1000),
         static_cast<unsigned long>(Pct(ttft, 0.90) / 1000),
         static_cast<unsigned long>(Pct(ttft, 0.99) / 1000));
  printf("  ITL usec: p50 %lu, p90 %lu, p99 %lu\n",
         static_cast<unsigned long>(Pct(itl, 0.50) / 1000),
         static_cast<unsigned long>(Pct(itl, 0.90) / 1000),
         static_cast<unsigned long>(Pct(itl, 0.99) / 1000));
  printf("{\"tok_s\": %.1f, \"ttft_us_p50\": %lu, \"ttft_us_p99\": %lu, "
         "\"itl_us_p50\": %lu, \"itl_us_p99\": %lu, \"streams\": %zu}\n",
         tok_s,
         static_cast<unsigned long>(Pct(ttft, 0.50) / 1000),
         static_cast<unsigned long>(Pct(ttft, 0.99) / 1000),
         static_cast<unsigned long>(Pct(itl, 0.50) / 1000),
         static_cast<unsigned long>(Pct(itl, 0.99) / 1000), streams);
  return errors > 0 ? 1 : 0;
}

}  // namespace

int main(int argc, char** argv) {
  signal(SIGINT, SignalHandler);

  static struct option long_opts[] = {
      {"concurrency-range", required_argument, nullptr, 1000},
      {"request-rate-range", required_argument, nullptr, 1001},
      {"request-distribution", required_argument, nullptr, 1002},
      {"request-intervals", required_argument, nullptr, 1003},
      {"binary-search", no_argument, nullptr, 1004},
      {"measurement-mode", required_argument, nullptr, 1005},
      {"measurement-request-count", required_argument, nullptr, 1006},
      {"percentile", required_argument, nullptr, 1007},
      {"input-data", required_argument, nullptr, 1008},
      {"shape", required_argument, nullptr, 1009},
      {"string-length", required_argument, nullptr, 1010},
      {"string-data", required_argument, nullptr, 1011},
      {"sequence-length", required_argument, nullptr, 1012},
      {"start-sequence-id", required_argument, nullptr, 1013},
      {"shared-memory", required_argument, nullptr, 1014},
      {"output-shared-memory-size", required_argument, nullptr, 1015},
      {"max-threads", required_argument, nullptr, 1016},
      {"service-kind", required_argument, nullptr, 1017},
      {"warmup-request-count", required_argument, nullptr, 1021},
      {"streaming", no_argument, nullptr, 1022},
      {"generative", no_argument, nullptr, 1023},
      {"generative-max-tokens", required_argument, nullptr, 1024},
      {"generative-no-coalesce", no_argument, nullptr, 1025},
      {"capi-library-path", required_argument, nullptr, 1018},
      {"capi-models", required_argument, nullptr, 1019},
      {"capi-repo-root", required_argument, nullptr, 1020},
      // Reference long spellings of the short options (main.cc:708-740):
      // both forms accepted, same semantics.
      {"async", no_argument, nullptr, 'a'},
      {"sync", no_argument, nullptr, 1026},
      {"measurement-interval", required_argument, nullptr, 'p'},
      {"stability-percentage", required_argument, nullptr, 's'},
      {"max-trials", required_argument, nullptr, 'r'},
      {"latency-threshold", required_argument, nullptr, 'l'},
      {"data-directory", required_argument, nullptr, 1008},
      {"grpc-compression-algorithm", required_argument, nullptr, 1027},
      {"model-signature-name", required_argument, nullptr, 1028},
      {"num-of-sequences", required_argument, nullptr, 1029},
      {"help", no_argument, nullptr, 'h'},
      {nullptr, 0, nullptr, 0}};

  Args args;
  int opt;
  while ((opt = getopt_long(argc, argv, "m:x:u:i:b:ap:s:r:l:f:vh", long_opts,
                            nullptr)) != -1) {
    switch (opt) {
      case 'm': args.model = optarg; break;
      case 'x': args.version = optarg; break;
      case 'u': args.url = optarg; args.url_set = true; break;
      case 'i': args.protocol = optarg; break;
      case 'b': args.batch_size = atoi(optarg); break;
      case 'a': args.async = true; break;
      case 'p': args.window_ms = strtoull(optarg, nullptr, 10); break;
      case 's': args.stability_pct = atof(optarg); break;
      case 'r': args.max_trials = strtoull(optarg, nullptr, 10); break;
      case 'l': args.latency_threshold_us =
                    strtoull(optarg, nullptr, 10) * 1000; break;
      case 'f': args.csv_path = optarg; break;
      case 'v': args.verbose = true; break;
      case 'h': Usage(); break;
      case 1000: {
        double a = 1, b = 1, c = 1;
        if (!ParseRange(optarg, &a, &b, &c))
          Usage("bad --concurrency-range, want start:end[:step]");
        args.has_concurrency = true;
        args.conc_start = a; args.conc_end = b; args.conc_step = c;
        break;
      }
      case 1001: {
        double a = 0, b = 0, c = 1;
        if (!ParseRange(optarg, &a, &b, &c))
          Usage("bad --request-rate-range, want start:end[:step]");
        args.has_rate = true;
        args.rate_start = a; args.rate_end = b; args.rate_step = c;
        break;
      }
      case 1002:
        if (strcmp(optarg, "poisson") == 0) {
          args.poisson = true;
        } else if (strcmp(optarg, "constant") != 0) {
          Usage("--request-distribution must be poisson or constant");
        }
        break;
      case 1003: args.intervals_file = optarg; break;
      case 1004: args.binary_search = true; break;
      case 1005:
        if (strcmp(optarg, "count_windows") == 0)
          args.mode = MeasurementMode::COUNT_WINDOWS;
        break;
      case 1006: args.request_count = strtoull(optarg, nullptr, 10); break;
      case 1007: args.percentile = atoll(optarg); break;
      case 1008: args.input_data = optarg; break;
      case 1009: {
        std::string spec(optarg);
        size_t colon = spec.rfind(':');
        if (colon == std::string::npos) Usage("bad --shape, want name:d1,d2");
        std::string name = spec.substr(0, colon);
        std::vector<int64_t> dims;
        std::stringstream ss(spec.substr(colon + 1));
        std::string tok;
        while (std::getline(ss, tok, ',')) dims.push_back(atoll(tok.c_str()));
        args.data_opts.shapes[name] = dims;
        break;
      }
      case 1010:
        args.data_opts.string_length = strtoull(optarg, nullptr, 10);
        break;
      case 1011: args.data_opts.string_data = optarg; break;
      case 1012: args.sequence_length = strtoull(optarg, nullptr, 10); break;
      case 1013:
        args.start_sequence_id = strtoull(optarg, nullptr, 10);
        break;
      case 1014:
        if (strcmp(optarg, "system") == 0) args.shm = SharedMemoryType::SYSTEM;
        else if (strcmp(optarg, "tpu") == 0) args.shm = SharedMemoryType::TPU;
        else if (strcmp(optarg, "none") != 0)
          Usage("--shared-memory must be none|system|tpu");
        break;
      case 1015: args.output_shm_size = strtoull(optarg, nullptr, 10); break;
      case 1016: args.max_threads = strtoull(optarg, nullptr, 10); break;
      case 1017:
        if (strcmp(optarg, "tpu_capi") == 0) args.kind = BackendKind::TPU_CAPI;
        else if (strcmp(optarg, "tpu_grpc") == 0)
          args.kind = BackendKind::TPU_GRPC;
        else if (strcmp(optarg, "tfserving") == 0)
          args.kind = BackendKind::TENSORFLOW_SERVING;
        else if (strcmp(optarg, "torchserve") == 0)
          args.kind = BackendKind::TORCHSERVE;
        else if (strcmp(optarg, "tpu_http") != 0)
          Usage("--service-kind must be "
                "tpu_http|tpu_grpc|tpu_capi|tfserving|torchserve");
        break;
      case 1018: args.capi_lib = optarg; break;
      case 1019: args.capi_models = optarg; break;
      case 1020: args.capi_repo_root = optarg; break;
      case 1021: args.warmup_requests = strtoull(optarg, nullptr, 10); break;
      case 1022: args.streaming = true; break;
      case 1023: args.generative = true; args.streaming = true; break;
      case 1025: args.gen_coalesce = false; break;
      case 1024:
        args.gen_max_tokens = strtoull(optarg, nullptr, 10);
        break;
      case 1026: args.async = false; break;
      case 1027:
        if (strcmp(optarg, "gzip") == 0)
          args.compression = tpuclient::GrpcCompression::GZIP;
        else if (strcmp(optarg, "deflate") == 0)
          args.compression = tpuclient::GrpcCompression::DEFLATE;
        else if (strcmp(optarg, "none") != 0)
          Usage("--grpc-compression-algorithm must be none|gzip|deflate");
        break;
      case 1028: args.signature_name = optarg; break;
      case 1029:
        args.num_of_sequences =
            std::max<size_t>(1, strtoull(optarg, nullptr, 10));
        break;
      default: Usage("unknown option");
    }
  }
  if (args.model.empty()) Usage("-m <model> is required");
  if (args.streaming) {
    // Streaming rides the gRPC bidi RPC; it is inherently async (the
    // stream callback completes requests), mirroring the reference's
    // constraint set (main.cc:1323).
    if (args.kind != BackendKind::TPU_GRPC && args.protocol != "grpc")
      Usage("--streaming requires --service-kind tpu_grpc (or -i grpc)");
    args.kind = BackendKind::TPU_GRPC;
    if (!args.url_set) args.url = "localhost:8001";
    args.async = true;
    if (args.shm != SharedMemoryType::NONE)
      Usage("--streaming does not support --shared-memory");
  }
  if (args.protocol == "grpc") {
    if (args.kind == BackendKind::TPU_HTTP) args.kind = BackendKind::TPU_GRPC;
    if (!args.url_set) args.url = "localhost:8001";
  } else if (args.protocol != "http") {
    Usage("-i must be http or grpc");
  }
  if (args.kind == BackendKind::TENSORFLOW_SERVING ||
      args.kind == BackendKind::TORCHSERVE) {
    // Capability guards mirroring the reference (main.cc:1197-1216): both
    // kinds are sync-only and have no shared-memory control plane;
    // torchserve additionally needs --input-data files to upload.
    if (args.async)
      Usage("--service-kind tfserving/torchserve is sync-only");
    if (args.shm != SharedMemoryType::NONE)
      Usage("--shared-memory is not supported with "
            "tfserving/torchserve kinds");
    if (args.kind == BackendKind::TORCHSERVE &&
        (args.input_data == "random" || args.input_data == "zero"))
      Usage("--service-kind torchserve requires --input-data with file "
            "paths");
    if (!args.url_set)
      args.url = args.kind == BackendKind::TENSORFLOW_SERVING
                     ? "localhost:8500" : "localhost:8080";
  }
  if (args.kind == BackendKind::TPU_CAPI) {
    // Sync-only like the reference's C-API kind (main.cc:1227-1248) —
    // but unlike the reference, the in-process engine has a full shm
    // control plane (system + tpu regions), so --shared-memory works here
    // and measures the no-network shm data path.
    if (args.async) Usage("--service-kind tpu_capi is sync-only");
    if (args.capi_models.empty()) args.capi_models = args.model;
  }

  // --- backend + parser -----------------------------------------------------
  if (!args.signature_name.empty()) {
    SetTfServeSignatureName(args.signature_name);
  }
  ClientBackendFactory factory(args.kind, args.url, args.verbose,
                               /*max_async_concurrency=*/32);
  factory.SetCApiOptions(args.capi_lib, args.capi_models,
                         args.capi_repo_root);
  std::unique_ptr<ClientBackend> meta_backend;
  Error err = factory.Create(&meta_backend);
  if (!err.IsOk()) {
    fprintf(stderr, "failed to create backend: %s\n", err.Message().c_str());
    return 1;
  }
  auto parser = std::make_shared<ModelParser>();
  {
    tpuclient::JsonPtr metadata, config;
    err = meta_backend->ModelMetadata(&metadata, args.model, args.version);
    if (err.IsOk())
      err = meta_backend->ModelConfig(&config, args.model, args.version);
    if (err.IsOk()) err = parser->Init(metadata, config);
    if (!err.IsOk()) {
      fprintf(stderr, "failed to load model info for '%s': %s\n",
              args.model.c_str(), err.Message().c_str());
      return 1;
    }
  }
  if (parser->MaxBatchSize() == 0 && args.batch_size > 1) {
    fprintf(stderr, "model does not support batching (max_batch_size 0)\n");
    return 1;
  }

  // --- data -----------------------------------------------------------------
  auto data_loader = std::make_shared<DataLoader>();
  args.data_opts.zero_data = args.input_data == "zero";
  if (args.input_data == "zero" || args.input_data == "random") {
    err = data_loader->GenerateData(*parser, args.data_opts);
  } else {
    struct stat st;
    if (stat(args.input_data.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
      err = data_loader->ReadDataFromDir(*parser, args.input_data,
                                         args.data_opts);
    } else {
      err = data_loader->ReadDataFromJson(*parser, args.input_data,
                                          args.data_opts);
    }
  }
  if (!err.IsOk()) {
    fprintf(stderr, "data error: %s\n", err.Message().c_str());
    return 1;
  }

  if (args.generative) {
    return RunGenerativeProfile(factory, *parser, args);
  }

  // --- manager --------------------------------------------------------------
  LoadOptions load_opts;
  load_opts.batch_size = args.batch_size;
  load_opts.async = args.async;
  load_opts.streaming = args.streaming;
  load_opts.max_threads = args.max_threads;
  load_opts.shm_type = args.shm;
  load_opts.output_shm_size = args.output_shm_size;
  load_opts.sequence_length = args.sequence_length;
  load_opts.start_sequence_id = args.start_sequence_id;
  load_opts.num_of_sequences = args.num_of_sequences;
  load_opts.compression = args.compression;

  std::unique_ptr<LoadManager> manager;
  enum class Mode { CONCURRENCY, RATE, CUSTOM } mode = Mode::CONCURRENCY;
  if (!args.intervals_file.empty()) {
    mode = Mode::CUSTOM;
    std::unique_ptr<CustomLoadManager> m;
    err = CustomLoadManager::Create(load_opts, args.intervals_file, factory,
                                    parser, data_loader, &m);
    manager = std::move(m);
  } else if (args.has_rate) {
    mode = Mode::RATE;
    std::unique_ptr<RequestRateManager> m;
    err = RequestRateManager::Create(
        load_opts,
        args.poisson ? Distribution::POISSON : Distribution::CONSTANT,
        factory, parser, data_loader, &m);
    manager = std::move(m);
  } else {
    std::unique_ptr<ConcurrencyManager> m;
    err = ConcurrencyManager::Create(load_opts, factory, parser, data_loader,
                                     &m);
    manager = std::move(m);
  }
  if (!err.IsOk()) {
    fprintf(stderr, "failed to create load manager: %s\n",
            err.Message().c_str());
    return 1;
  }

  if (args.warmup_requests > 0) {
    fprintf(stderr, "sending %zu warmup request(s)...\n",
            args.warmup_requests);
    err = manager->WarmUp(args.warmup_requests);
    if (!err.IsOk()) {
      fprintf(stderr, "warmup error: %s\n", err.Message().c_str());
      return 1;
    }
  }

  // --- profiler -------------------------------------------------------------
  InferenceProfiler::Options popts;
  popts.stability_threshold = args.stability_pct / 100.0;
  popts.measurement_window_ms = args.window_ms;
  popts.measurement_mode = args.mode;
  popts.measurement_request_count = args.request_count;
  popts.max_trials = args.max_trials;
  popts.latency_threshold_us = args.latency_threshold_us;
  popts.percentile = args.percentile;
  popts.verbose = args.verbose;

  std::unique_ptr<ClientBackend> stats_backend;
  err = factory.Create(&stats_backend);
  if (!err.IsOk()) {
    fprintf(stderr, "failed to create stats backend: %s\n",
            err.Message().c_str());
    return 1;
  }
  InferenceProfiler profiler(popts, parser, std::move(stats_backend),
                             manager.get());

  const char* kind_name =
      args.kind == BackendKind::TPU_GRPC            ? "grpc"
      : args.kind == BackendKind::TPU_CAPI          ? "in-process C API"
      : args.kind == BackendKind::TENSORFLOW_SERVING ? "tfserving (grpc)"
      : args.kind == BackendKind::TORCHSERVE        ? "torchserve (http)"
                                                    : "http";
  printf("*** Measurement Settings ***\n");
  printf("  Model: %s, batch size: %d, protocol: %s, mode: %s\n",
         args.model.c_str(), args.batch_size, kind_name,
         args.async ? "async" : "sync");
  printf("  Window: %lu ms (%s), stability: %.0f%%, max trials: %zu\n\n",
         static_cast<unsigned long>(args.window_ms),
         args.mode == MeasurementMode::TIME_WINDOWS ? "time" : "count",
         args.stability_pct, args.max_trials);

  std::vector<PerfStatus> results;
  switch (mode) {
    case Mode::CONCURRENCY:
      err = profiler.ProfileConcurrency(args.conc_start, args.conc_end,
                                        args.conc_step, args.binary_search,
                                        &results);
      break;
    case Mode::RATE:
      err = profiler.ProfileRate(args.rate_start, args.rate_end,
                                 args.rate_step, args.binary_search, &results);
      break;
    case Mode::CUSTOM:
      err = profiler.ProfileCustom(&results);
      break;
  }
  if (!err.IsOk()) {
    fprintf(stderr, "profiling failed: %s\n", err.Message().c_str());
    return 1;
  }

  printf("\n*** Results ***\n");
  for (const auto& st : results) {
    PrintStatus(st);
    printf("\n");
  }
  if (!args.csv_path.empty()) WriteCsv(args, results);
  return 0;
}
