// Kind=TENSORFLOW_SERVING: the perf harness speaking TFS PredictionService
// over the in-tree gRPC transport.
//
// Counterpart of the reference's tensorflow_serving backend
// (/root/reference/src/c++/perf_analyzer/client_backend/tensorflow_serving/
// tfserve_client_backend.h:52-110, tfserve_grpc_client.{h,cc} — a dedicated
// grpc++ PredictionService client with perf↔TFS dtype conversion,
// perf_utils.h:78-79). Here the messages are the re-authored minimal protos
// (protocol/protos/tfs_predict.proto) and the transport is GrpcUnaryCall
// over src/h2.cc. Design difference: instead of special-casing the model
// parser (reference InitTFServe, model_parser.cc:208-296), this backend
// converts TFS GetModelMetadata signature_defs into v2-shaped metadata JSON
// so the generic parser path handles all kinds uniformly.

#include <cstring>

#include "client_backend.h"
#include "tfs_predict.pb.h"
#include "tpuclient/grpc_client.h"

// h2.h lives in src/ (internal transport header).
#include "../src/h2.h"

using tpuclient::Error;
using tpuclient::JsonPtr;

namespace tpuperf {

namespace {

namespace tfs = tensorflow::serving;

// --model-signature-name override (process-wide; set by the CLI before
// any backend exists, so no synchronization is needed).
std::string g_signature_name = "serving_default";

struct DtypePair { const char* v2; tfs::DataType tf; };
constexpr DtypePair kDtypes[] = {
    {"FP32", tfs::DT_FLOAT},   {"FP64", tfs::DT_DOUBLE},
    {"INT32", tfs::DT_INT32},  {"UINT8", tfs::DT_UINT8},
    {"INT16", tfs::DT_INT16},  {"INT8", tfs::DT_INT8},
    {"BYTES", tfs::DT_STRING}, {"INT64", tfs::DT_INT64},
    {"BOOL", tfs::DT_BOOL},    {"UINT16", tfs::DT_UINT16},
    {"FP16", tfs::DT_HALF},    {"UINT32", tfs::DT_UINT32},
    {"UINT64", tfs::DT_UINT64},
};

tfs::DataType V2ToTfs(const std::string& v2) {
  for (const auto& p : kDtypes)
    if (v2 == p.v2) return p.tf;
  return tfs::DT_INVALID;
}

const char* TfsToV2(tfs::DataType tf) {
  for (const auto& p : kDtypes)
    if (tf == p.tf) return p.v2;
  return nullptr;
}

size_t TfsDtypeSize(tfs::DataType tf) {
  switch (tf) {
    case tfs::DT_FLOAT: return 4;
    case tfs::DT_DOUBLE: return 8;
    case tfs::DT_INT32: return 4;
    case tfs::DT_UINT8: return 1;
    case tfs::DT_INT16: return 2;
    case tfs::DT_INT8: return 1;
    case tfs::DT_INT64: return 8;
    case tfs::DT_BOOL: return 1;
    case tfs::DT_UINT16: return 2;
    case tfs::DT_HALF: return 2;
    case tfs::DT_UINT32: return 4;
    case tfs::DT_UINT64: return 8;
    default: return 0;
  }
}

// Packs a TensorProto's payload into contiguous little-endian bytes: the
// fast path is tensor_content verbatim; typed repeated fields are
// materialized (TFS answers with either form).
void PackTensor(const tfs::TensorProto& t, std::string* out) {
  if (!t.tensor_content().empty()) {
    *out = t.tensor_content();
    return;
  }
  auto append = [out](const void* p, size_t n) {
    out->append(reinterpret_cast<const char*>(p), n);
  };
  switch (t.dtype()) {
    case tfs::DT_FLOAT:
      for (float v : t.float_val()) append(&v, 4);
      break;
    case tfs::DT_DOUBLE:
      for (double v : t.double_val()) append(&v, 8);
      break;
    case tfs::DT_INT32:
    case tfs::DT_INT16:
    case tfs::DT_INT8:
    case tfs::DT_UINT8:
    case tfs::DT_UINT16: {
      size_t sz = TfsDtypeSize(t.dtype());
      for (int32_t v : t.int_val()) append(&v, sz);  // LE truncation
      break;
    }
    case tfs::DT_HALF:
      // half_val carries one fp16 pattern in the low 16 bits per element.
      for (int32_t v : t.half_val()) append(&v, 2);
      break;
    case tfs::DT_INT64:
      for (int64_t v : t.int64_val()) append(&v, 8);
      break;
    case tfs::DT_BOOL:
      for (bool v : t.bool_val()) {
        char b = v ? 1 : 0;
        append(&b, 1);
      }
      break;
    case tfs::DT_UINT32:
      for (uint32_t v : t.uint32_val()) append(&v, 4);
      break;
    case tfs::DT_UINT64:
      for (uint64_t v : t.uint64_val()) append(&v, 8);
      break;
    case tfs::DT_STRING:
      for (const std::string& s : t.string_val()) {
        uint32_t len = uint32_t(s.size());
        append(&len, 4);  // v2 BYTES framing: 4-byte LE length prefix
        out->append(s);
      }
      break;
    default:
      break;
  }
}

class TfsInferResult : public tpuclient::InferResult {
 public:
  TfsInferResult(std::shared_ptr<tfs::PredictResponse> resp, Error status,
                 std::string request_id)
      : resp_(std::move(resp)), status_(std::move(status)),
        request_id_(std::move(request_id)) {
    if (resp_ != nullptr) {
      for (const auto& kv : resp_->outputs()) {
        PackTensor(kv.second, &packed_[kv.first]);
      }
    }
  }

  Error ModelName(std::string* name) const override {
    *name = resp_ != nullptr ? resp_->model_spec().name() : "";
    return Error::Success();
  }
  Error ModelVersion(std::string* version) const override {
    *version = resp_ != nullptr && resp_->model_spec().has_version()
                   ? std::to_string(resp_->model_spec().version().value())
                   : "";
    return Error::Success();
  }
  Error Id(std::string* id) const override {
    *id = request_id_;  // TFS carries no request id; echo the client's
    return Error::Success();
  }
  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override {
    const tfs::TensorProto* t = Find(output_name);
    if (t == nullptr) return Error("no output '" + output_name + "'", 400);
    shape->clear();
    for (const auto& d : t->tensor_shape().dim()) shape->push_back(d.size());
    return Error::Success();
  }
  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override {
    const tfs::TensorProto* t = Find(output_name);
    if (t == nullptr) return Error("no output '" + output_name + "'", 400);
    const char* v2 = TfsToV2(t->dtype());
    *datatype = v2 != nullptr ? v2 : "UNKNOWN";
    return Error::Success();
  }
  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override {
    auto it = packed_.find(output_name);
    if (it == packed_.end())
      return Error("no output '" + output_name + "'", 400);
    *buf = reinterpret_cast<const uint8_t*>(it->second.data());
    *byte_size = it->second.size();
    return Error::Success();
  }
  Error RequestStatus() const override { return status_; }
  std::string DebugString() const override {
    return resp_ != nullptr ? resp_->ShortDebugString() : status_.Message();
  }

 private:
  const tfs::TensorProto* Find(const std::string& name) const {
    if (resp_ == nullptr) return nullptr;
    auto it = resp_->outputs().find(name);
    return it == resp_->outputs().end() ? nullptr : &it->second;
  }

  std::shared_ptr<tfs::PredictResponse> resp_;
  Error status_;
  std::string request_id_;
  std::map<std::string, std::string> packed_;
};

class TfServeClientBackend : public ClientBackend {
 public:
  static Error Create(const std::string& url, bool verbose,
                      std::unique_ptr<ClientBackend>* backend) {
    (void)verbose;
    auto b = std::unique_ptr<TfServeClientBackend>(new TfServeClientBackend());
    std::string host;
    int port;
    tpuclient::SplitUrl(url, /*default_port=*/8500, &host, &port);
    b->authority_ = host.find(':') != std::string::npos
                        ? "[" + host + "]:" + std::to_string(port)
                        : host + ":" + std::to_string(port);
    b->conn_ = std::make_shared<tpuclient::h2::Connection>();
    Error err = b->conn_->Connect(host, port);
    if (!err.IsOk()) return err;
    *backend = std::move(b);
    return Error::Success();
  }

  Error ServerExtensions(std::vector<std::string>* extensions) override {
    extensions->clear();  // TFS has no v2 extension discovery
    return Error::Success();
  }

  // TFS GetModelMetadata(signature_def) → v2-shaped metadata JSON, so the
  // generic model parser consumes one format for every kind.
  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string& version) override {
    tfs::GetModelMetadataRequest req;
    req.mutable_model_spec()->set_name(model_name);
    req.mutable_model_spec()->set_signature_name(g_signature_name);
    if (!version.empty())
      req.mutable_model_spec()->mutable_version()->set_value(
          atoll(version.c_str()));
    req.add_metadata_field("signature_def");
    tfs::GetModelMetadataResponse resp;
    Error err = tpuclient::GrpcUnaryCall(
        conn_.get(), authority_,
        "/tensorflow.serving.PredictionService/GetModelMetadata", req, &resp);
    if (!err.IsOk()) return err;

    auto it = resp.metadata().find("signature_def");
    if (it == resp.metadata().end())
      return Error("TFS metadata carries no signature_def", 400);
    tfs::SignatureDefMap sigmap;
    if (!it->second.UnpackTo(&sigmap))
      return Error("failed to unpack SignatureDefMap", 400);
    auto sit = sigmap.signature_def().find(g_signature_name);
    if (sit == sigmap.signature_def().end())
      return Error("signature '" + g_signature_name +
                       "' not found in TFS metadata",
                   400);

    auto tensor_json = [](const std::string& name,
                          const tfs::TensorInfo& info) {
      JsonPtr t = tpuclient::Json::MakeObject();
      t->Set("name", name);
      const char* v2 = TfsToV2(info.dtype());
      t->Set("datatype", v2 != nullptr ? v2 : "UNKNOWN");
      JsonPtr dims = tpuclient::Json::MakeArray();
      if (!info.tensor_shape().unknown_rank()) {
        for (const auto& d : info.tensor_shape().dim())
          dims->Append(tpuclient::Json::MakeInt(d.size()));
      }
      t->Set("shape", dims);
      return t;
    };
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", model_name);
    out->Set("platform", "tensorflow_serving");
    JsonPtr inputs = tpuclient::Json::MakeArray();
    for (const auto& kv : sit->second.inputs())
      inputs->Append(tensor_json(kv.first, kv.second));
    out->Set("inputs", inputs);
    JsonPtr outputs = tpuclient::Json::MakeArray();
    for (const auto& kv : sit->second.outputs())
      outputs->Append(tensor_json(kv.first, kv.second));
    out->Set("outputs", outputs);
    *metadata = out;
    return Error::Success();
  }

  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string& version) override {
    (void)version;
    // TFS exposes no Triton-style config; minimal object (no batching
    // metadata — the harness's --batch-size flag governs, as in the
    // reference's InitTFServe, model_parser.cc:221-223).
    JsonPtr out = tpuclient::Json::MakeObject();
    out->Set("name", model_name);
    out->Set("max_batch_size", int64_t(0));
    *config = out;
    return Error::Success();
  }

  Error Infer(tpuclient::InferResult** result,
              const tpuclient::InferOptions& options,
              const std::vector<tpuclient::InferInput*>& inputs,
              const std::vector<const tpuclient::InferRequestedOutput*>&
                  outputs) override {
    tfs::PredictRequest req;
    req.mutable_model_spec()->set_name(options.model_name);
    req.mutable_model_spec()->set_signature_name(g_signature_name);
    if (!options.model_version.empty())
      req.mutable_model_spec()->mutable_version()->set_value(
          atoll(options.model_version.c_str()));

    for (const tpuclient::InferInput* input : inputs) {
      if (input->IsSharedMemory())
        return Error("shared memory is not supported with the "
                     "tensorflow_serving kind",
                     400);
      tfs::TensorProto& t = (*req.mutable_inputs())[input->Name()];
      tfs::DataType dt = V2ToTfs(input->Datatype());
      if (dt == tfs::DT_INVALID)
        return Error("dtype " + input->Datatype() +
                         " unsupported for tensorflow_serving",
                     400);
      t.set_dtype(dt);
      for (int64_t d : input->Shape())
        t.mutable_tensor_shape()->add_dim()->set_size(d);
      if (dt == tfs::DT_STRING) {
        // Re-split the v2 length-prefixed BYTES stream into string_val.
        std::string flat;
        input->CopyTo(&flat);
        size_t pos = 0;
        while (pos + 4 <= flat.size()) {
          uint32_t len;
          memcpy(&len, flat.data() + pos, 4);
          pos += 4;
          if (pos + len > flat.size())
            return Error("malformed BYTES input '" + input->Name() + "'",
                         400);
          t.add_string_val(flat.substr(pos, len));
          pos += len;
        }
      } else {
        std::string* content = t.mutable_tensor_content();
        content->reserve(input->TotalByteSize());
        for (const auto& seg : input->Buffers())
          content->append(reinterpret_cast<const char*>(seg.first),
                          seg.second);
      }
    }
    for (const tpuclient::InferRequestedOutput* o : outputs)
      req.add_output_filter(o->Name());

    auto resp = std::make_shared<tfs::PredictResponse>();
    Error err = tpuclient::GrpcUnaryCall(
        conn_.get(), authority_,
        "/tensorflow.serving.PredictionService/Predict", req, resp.get(),
        options.client_timeout_us);
    *result = new TfsInferResult(err.IsOk() ? resp : nullptr, err,
                                 options.request_id);
    return err;
  }

  Error AsyncInfer(tpuclient::OnCompleteFn, const tpuclient::InferOptions&,
                   const std::vector<tpuclient::InferInput*>&,
                   const std::vector<const tpuclient::InferRequestedOutput*>&)
      override {
    return Error("async is not supported with the tensorflow_serving kind "
                 "(reference main.cc:1197-1206)",
                 400);
  }

  Error ModelInferenceStatistics(std::map<std::string, ModelStatistics>*,
                                 const std::string&) override {
    return Error("server-side statistics are not available from "
                 "TensorFlow Serving",
                 400);
  }

  Error ClientInferStat(tpuclient::InferStat* stat) override {
    *stat = tpuclient::InferStat();
    return Error::Success();
  }

  bool SupportsAsync() const override { return false; }

 private:
  std::shared_ptr<tpuclient::h2::Connection> conn_;
  std::string authority_;
};

}  // namespace

Error CreateTfServeBackend(const std::string& url, bool verbose,
                           std::unique_ptr<ClientBackend>* backend) {
  return TfServeClientBackend::Create(url, verbose, backend);
}

void SetTfServeSignatureName(const std::string& name) {
  g_signature_name = name;
}

}  // namespace tpuperf
