// Test-data supply for the perf harness.
//
// Counterpart of the reference's data_loader.{h,cc}
// (/root/reference/src/c++/perf_analyzer/data_loader.h:40-107): synthetic
// random/zero tensors, random or fixed strings for BYTES, and user-supplied
// multi-stream JSON data ({"data": [stream][step]{input: ...}} or the flat
// one-stream form). Data is materialized once into wire-format byte strings
// and referenced zero-copy by every request the load managers build.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "model_parser.h"
#include "tpuclient/error.h"

namespace tpuperf {

class DataLoader {
 public:
  struct Options {
    bool zero_data = false;           // zeros instead of random
    size_t string_length = 16;        // random BYTES element length
    std::string string_data;          // fixed BYTES element (overrides random)
    uint64_t seed = 2024;
    // Shape overrides for dynamic dims: name -> concrete dims.
    std::map<std::string, std::vector<int64_t>> shapes;
  };

  // Synthetic generation for every model input (reference GenerateData,
  // data_loader.cc:133-200).
  tpuclient::Error GenerateData(const ModelParser& parser,
                                const Options& opts);

  // Load {"data": ...} JSON. Accepts [ {input: value} ... ] (one stream,
  // many steps) or [ [ {input: value} ... ] ... ] (stream-major).
  tpuclient::Error ReadDataFromJson(const ModelParser& parser,
                                    const std::string& path,
                                    const Options& opts);

  // Load a directory of per-input files (reference ReadDataFromDir,
  // data_loader.cc:41-69): one stream, one step; each non-BYTES input reads
  // raw little-endian bytes from `<dir>/<input name>`, BYTES inputs read a
  // text file of one string per line, serialized with length prefixes.
  tpuclient::Error ReadDataFromDir(const ModelParser& parser,
                                   const std::string& dir,
                                   const Options& opts);

  size_t StreamCount() const { return data_.size(); }
  size_t StepCount(size_t stream) const {
    return stream < data_.size() ? data_[stream].size() : 0;
  }

  // Wire-format bytes + concrete shape for one input at (stream, step).
  tpuclient::Error GetInputData(const std::string& name, size_t stream,
                                size_t step, const uint8_t** data,
                                size_t* byte_size,
                                std::vector<int64_t>* shape) const;

 private:
  struct TensorData {
    std::string bytes;            // wire format (BYTES incl. length prefixes)
    std::vector<int64_t> shape;
  };
  // data_[stream][step][input_name]
  std::vector<std::vector<std::map<std::string, TensorData>>> data_;

  tpuclient::Error MakeTensor(const ModelTensor& tensor, const Options& opts,
                              uint64_t salt, TensorData* out);
};

}  // namespace tpuperf
