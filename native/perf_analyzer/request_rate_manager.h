// Open-loop load: requests fired on a pre-generated timestamp schedule.
//
// Counterpart of the reference's request_rate_manager.{h,cc}
// (/root/reference/src/c++/perf_analyzer/request_rate_manager.cc:113-357):
// a schedule of send offsets (Poisson or constant inter-arrival) walked by
// worker threads with stride = thread count; a request sent after its slot
// is marked `delayed`. Async mode doesn't wait for completions — that's
// what makes the loop open.
#pragma once

#include "load_manager.h"

namespace tpuperf {

class RequestRateManager : public LoadManager {
 public:
  static tpuclient::Error Create(const LoadOptions& options,
                                 Distribution distribution,
                                 const ClientBackendFactory& factory,
                                 std::shared_ptr<ModelParser> parser,
                                 std::shared_ptr<DataLoader> data_loader,
                                 std::unique_ptr<RequestRateManager>* manager);
  ~RequestRateManager() override;

  tpuclient::Error ChangeRequestRate(double request_rate);

  // Whether the generated load kept up with the schedule in the last swap
  // window (reference delayed_ flag).
  bool HasDelayedRequests() const { return delayed_.load(); }

 protected:
  RequestRateManager(const LoadOptions& options, Distribution distribution,
                     const ClientBackendFactory& factory,
                     std::shared_ptr<ModelParser> parser,
                     std::shared_ptr<DataLoader> data_loader)
      : LoadManager(options, factory, std::move(parser),
                    std::move(data_loader)),
        distribution_(distribution) {}

  // Generates `schedule_`: absolute ns offsets from the epoch start
  // (reference GenerateSchedule, request_rate_manager.cc:113-134).
  virtual tpuclient::Error GenerateSchedule(double request_rate);

  void StartWorkers(size_t n_threads);
  void PauseWorkers();
  void WorkerLoop(std::shared_ptr<ThreadStat> stat,
                  std::shared_ptr<ThreadConfig> config);

  Distribution distribution_;
  // Send offsets (ns). Immutable snapshot: GenerateSchedule installs a new
  // vector under wake_mutex_ and workers copy the shared_ptr per iteration,
  // so a rate change never mutates a schedule a worker is reading.
  std::shared_ptr<const std::vector<uint64_t>> schedule_;
  std::atomic<uint64_t> epoch_ns_{0};
  std::atomic<bool> running_{false};
  std::atomic<bool> delayed_{false};
  std::mutex wake_mutex_;
  std::condition_variable wake_cv_;
};

}  // namespace tpuperf
