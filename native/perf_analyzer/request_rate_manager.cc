#include "request_rate_manager.h"

using tpuclient::Error;

namespace tpuperf {

Error RequestRateManager::Create(
    const LoadOptions& options, Distribution distribution,
    const ClientBackendFactory& factory, std::shared_ptr<ModelParser> parser,
    std::shared_ptr<DataLoader> data_loader,
    std::unique_ptr<RequestRateManager>* manager) {
  auto m = std::unique_ptr<RequestRateManager>(new RequestRateManager(
      options, distribution, factory, std::move(parser),
      std::move(data_loader)));
  *manager = std::move(m);
  return Error::Success();
}

RequestRateManager::~RequestRateManager() {
  exit_.store(true);
  running_.store(true);  // release any paused workers so they can exit
  wake_cv_.notify_all();
  StopWorkerThreads();
}

Error RequestRateManager::GenerateSchedule(double request_rate) {
  // Two seconds of schedule, repeated cyclically by the workers (reference
  // generates max_trials * measurement windows; cyclic repeat is equivalent
  // for constant/Poisson and keeps memory bounded).
  if (request_rate <= 0) return Error("request rate must be > 0", 400);
  ScheduleDistribution dist(distribution_, request_rate, 42);
  auto schedule = std::make_shared<std::vector<uint64_t>>();
  uint64_t t = 0;
  uint64_t horizon = 2'000'000'000ULL;
  while (t < horizon || schedule->size() < 8) {
    t += dist.NextGapNs();
    schedule->push_back(t);
  }
  std::lock_guard<std::mutex> lk(wake_mutex_);
  schedule_ = std::move(schedule);
  return Error::Success();
}

Error RequestRateManager::ChangeRequestRate(double request_rate) {
  PauseWorkers();
  Error err = GenerateSchedule(request_rate);
  if (!err.IsOk()) return err;
  size_t n_threads =
      std::min<size_t>(options_.max_threads,
                       std::max<size_t>(1, static_cast<size_t>(
                                               request_rate / 100) + 1));
  if (is_sequence_) {
    // Each context is one live sequence; --num-of-sequences bounds the
    // total, so never spin up more workers than sequences.
    n_threads = std::min<size_t>(
        n_threads, std::max<size_t>(1, options_.num_of_sequences));
  }
  StartWorkers(n_threads);
  return Error::Success();
}

void RequestRateManager::PauseWorkers() {
  running_.store(false);
}

void RequestRateManager::StartWorkers(size_t n_threads) {
  while (threads_.size() < n_threads) {
    size_t idx = threads_.size();
    auto stat = std::make_shared<ThreadStat>();
    auto config = std::make_shared<ThreadConfig>();
    config->index = idx;
    Error err = factory_.Create(&config->backend);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lk(stat->mu);
      stat->status = err;
      return;
    }
    if (options_.shm_type != SharedMemoryType::NONE && !shm_ready_) {
      err = InitSharedMemory(config->backend.get());
      if (!err.IsOk()) {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
    }
    thread_stats_.push_back(stat);
    thread_configs_.push_back(config);
    threads_.emplace_back(&RequestRateManager::WorkerLoop, this, stat, config);
  }
  for (auto& config : thread_configs_) {
    config->stride = threads_.size();
    if (is_sequence_) {
      // Distribute --num-of-sequences across the workers: context = one
      // live sequence, so the per-thread context cap bounds the total
      // number of distinct concurrent sequences (reference
      // --num-of-sequences semantics under request-rate load).
      size_t n = std::max<size_t>(1, options_.num_of_sequences);
      size_t per = n / threads_.size();
      size_t extra = n % threads_.size();
      config->max_ctxs = std::max<size_t>(
          1, per + (config->index < extra ? 1 : 0));
    }
  }
  delayed_.store(false);
  epoch_ns_.store(NowNs());
  running_.store(true);
  wake_cv_.notify_all();
}

void RequestRateManager::WorkerLoop(std::shared_ptr<ThreadStat> stat,
                                    std::shared_ptr<ThreadConfig> config) {
  size_t slot = config->index;
  uint64_t cycle = 0;  // how many times the schedule wrapped
  uint64_t seen_epoch = 0;
  auto inflight = std::make_shared<std::atomic<size_t>>(0);

  while (!exit_.load()) {
    if (!running_.load()) {
      std::unique_lock<std::mutex> lk(wake_mutex_);
      wake_cv_.wait_for(lk, std::chrono::milliseconds(20), [&]() {
        return exit_.load() || running_.load();
      });
      continue;
    }
    uint64_t epoch = epoch_ns_.load();
    if (epoch != seen_epoch) {
      seen_epoch = epoch;
      slot = config->index;
      cycle = 0;
    }
    std::shared_ptr<const std::vector<uint64_t>> schedule;
    {
      std::lock_guard<std::mutex> lk(wake_mutex_);
      schedule = schedule_;
    }
    if (!schedule || schedule->empty()) continue;

    uint64_t cycle_span = schedule->back();
    uint64_t offset =
        (*schedule)[slot % schedule->size()] + cycle * cycle_span;
    uint64_t due = epoch + offset;
    uint64_t now = NowNs();
    if (now < due) {
      uint64_t wait_ns = due - now;
      std::this_thread::sleep_for(std::chrono::nanoseconds(
          std::min<uint64_t>(wait_ns, 20'000'000)));
      if (due - now > 20'000'000) continue;  // re-check running/exit
    }
    bool was_delayed = NowNs() > due + 2'000'000;  // >2ms behind schedule
    if (was_delayed) delayed_.store(true);

    // context: sync uses one, async finds a free one
    InferContext* ctx = nullptr;
    for (auto& c : config->ctxs) {
      if (!c->inflight) {
        ctx = c.get();
        break;
      }
    }
    if (ctx == nullptr && config->ctxs.size() >= config->max_ctxs) {
      // Sequence-pool cap (--num-of-sequences): all of this worker's
      // sequences are mid-request; wait for one to go idle instead of
      // opening a new sequence beyond the requested pool.
      while (!exit_.load() && running_.load() && ctx == nullptr) {
        for (auto& c : config->ctxs) {
          if (!c->inflight) {
            ctx = c.get();
            break;
          }
        }
        if (ctx == nullptr) {
          std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
      }
      if (ctx == nullptr) continue;  // paused or exiting
    }
    if (ctx == nullptr) {
      Error err = MakeContext(config.get(), &ctx);
      if (!err.IsOk()) {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
    }
    Error err = PrepareRequest(ctx);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lk(stat->mu);
      stat->status = err;
      return;
    }

    if (options_.async) {
      ctx->inflight = true;
      ctx->start_ns = NowNs();
      bool seq_end = ctx->options->sequence_end;
      ThreadStat* stat_ptr = stat.get();
      inflight->fetch_add(1);
      err = config->backend->AsyncInfer(
          [this, ctx, stat_ptr, seq_end, was_delayed, inflight](
              tpuclient::InferResult* result) {
            uint64_t end = NowNs();
            Error status =
                result != nullptr ? result->RequestStatus() : Error("null");
            delete result;
            if (status.IsOk()) {
              RecordRequest(stat_ptr, ctx->start_ns, end, seq_end,
                            was_delayed);
            } else {
              std::lock_guard<std::mutex> lk(stat_ptr->mu);
              stat_ptr->status = status;
            }
            ctx->inflight = false;
            inflight->fetch_sub(1);
          },
          *ctx->options, ctx->inputs, ctx->outputs);
      if (!err.IsOk()) {
        ctx->inflight = false;
        inflight->fetch_sub(1);
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
    } else {
      tpuclient::InferResult* result = nullptr;
      uint64_t start = NowNs();
      err = config->backend->Infer(&result, *ctx->options, ctx->inputs,
                                   ctx->outputs);
      uint64_t end = NowNs();
      if (err.IsOk() && result != nullptr) err = result->RequestStatus();
      delete result;
      if (err.IsOk()) {
        RecordRequest(stat.get(), start, end, ctx->options->sequence_end,
                      was_delayed);
      } else {
        std::lock_guard<std::mutex> lk(stat->mu);
        stat->status = err;
        return;
      }
    }

    slot += config->stride;
    cycle = slot / schedule->size();
  }
  while (inflight->load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
}

}  // namespace tpuperf
