// Kind=TPU_CAPI: runs the TPU serving engine IN-PROCESS by dlopen'ing
// libtpuserver.so and binding its C API — no network anywhere in the loop.
//
// Counterpart of the reference's triton_c_api backend, which dlopens
// libtritonserver.so and binds ~45 TRITONSERVER_* entrypoints
// (/root/reference/src/c++/perf_analyzer/client_backend/triton_c_api/
// shared_library.cc:37-89, triton_loader.h:83-255, triton_loader.cc:251).
// Like the reference (main.cc:1227-1248): sync-only. Unlike the reference,
// the shm control plane IS exposed in-process (system + tpu regions), so
// the harness's --shared-memory modes measure the engine's shm data path
// with zero network; plain in-process tensors are zero-copy by construction.

#include <dlfcn.h>

#include <cstring>
#include <mutex>

#include "client_backend.h"
#include "../capi/tpu_server_capi.h"

using tpuclient::Error;
using tpuclient::JsonPtr;

namespace tpuperf {

namespace {

// Singleton loader: one dlopen'd library + one engine per process, shared by
// every worker's backend instance (reference TritonLoader singleton).
class TpuServerLibrary {
 public:
  static TpuServerLibrary& Get() {
    static TpuServerLibrary lib;
    return lib;
  }

  Error Init(const std::string& lib_path, const std::string& models,
             const std::string& repo_root) {
    std::lock_guard<std::mutex> lk(mutex_);
    if (server_ != nullptr) return Error::Success();
    handle_ = dlopen(lib_path.c_str(), RTLD_NOW | RTLD_GLOBAL);
    if (handle_ == nullptr) {
      return Error(std::string("dlopen failed: ") + dlerror());
    }
    auto bind = [this](const char* name) -> void* {
      void* fn = dlsym(handle_, name);
      if (fn == nullptr) bind_error_ = name;
      return fn;
    };
    new_ = reinterpret_cast<decltype(&TpuServerNew)>(bind("TpuServerNew"));
    delete_ =
        reinterpret_cast<decltype(&TpuServerDelete)>(bind("TpuServerDelete"));
    metadata_ = reinterpret_cast<decltype(&TpuServerModelMetadataJson)>(
        bind("TpuServerModelMetadataJson"));
    config_ = reinterpret_cast<decltype(&TpuServerModelConfigJson)>(
        bind("TpuServerModelConfigJson"));
    stats_ = reinterpret_cast<decltype(&TpuServerModelStatisticsJson)>(
        bind("TpuServerModelStatisticsJson"));
    infer_ = reinterpret_cast<decltype(&TpuServerInfer)>(
        bind("TpuServerInfer"));
    resp_json_ = reinterpret_cast<decltype(&TpuServerResponseJson)>(
        bind("TpuServerResponseJson"));
    resp_count_ = reinterpret_cast<decltype(&TpuServerResponseOutputCount)>(
        bind("TpuServerResponseOutputCount"));
    resp_output_ = reinterpret_cast<decltype(&TpuServerResponseOutput)>(
        bind("TpuServerResponseOutput"));
    resp_delete_ = reinterpret_cast<decltype(&TpuServerResponseDelete)>(
        bind("TpuServerResponseDelete"));
    free_ = reinterpret_cast<decltype(&TpuServerFreeString)>(
        bind("TpuServerFreeString"));
    reg_sys_shm_ = reinterpret_cast<decltype(&TpuServerRegisterSystemShm)>(
        bind("TpuServerRegisterSystemShm"));
    unreg_sys_shm_ =
        reinterpret_cast<decltype(&TpuServerUnregisterSystemShm)>(
            bind("TpuServerUnregisterSystemShm"));
    reg_tpu_shm_ = reinterpret_cast<decltype(&TpuServerRegisterTpuShm)>(
        bind("TpuServerRegisterTpuShm"));
    unreg_tpu_shm_ = reinterpret_cast<decltype(&TpuServerUnregisterTpuShm)>(
        bind("TpuServerUnregisterTpuShm"));
    if (!bind_error_.empty()) {
      return Error("missing symbol in " + lib_path + ": " + bind_error_);
    }
    char* err = new_(&server_, models.c_str(),
                     repo_root.empty() ? nullptr : repo_root.c_str());
    if (err != nullptr) {
      std::string msg(err);
      free_(err);
      server_ = nullptr;
      return Error("TpuServerNew failed: " + msg);
    }
    return Error::Success();
  }

  // Wraps a C-API call returning a malloc'd error string.
  Error Wrap(char* err) {
    if (err == nullptr) return Error::Success();
    std::string msg(err);
    free_(err);
    return Error(msg, 400);
  }

  TpuServer* server() { return server_; }

  decltype(&TpuServerModelMetadataJson) metadata_ = nullptr;
  decltype(&TpuServerModelConfigJson) config_ = nullptr;
  decltype(&TpuServerModelStatisticsJson) stats_ = nullptr;
  decltype(&TpuServerInfer) infer_ = nullptr;
  decltype(&TpuServerResponseJson) resp_json_ = nullptr;
  decltype(&TpuServerResponseOutputCount) resp_count_ = nullptr;
  decltype(&TpuServerResponseOutput) resp_output_ = nullptr;
  decltype(&TpuServerResponseDelete) resp_delete_ = nullptr;
  decltype(&TpuServerFreeString) free_ = nullptr;
  decltype(&TpuServerRegisterSystemShm) reg_sys_shm_ = nullptr;
  decltype(&TpuServerUnregisterSystemShm) unreg_sys_shm_ = nullptr;
  decltype(&TpuServerRegisterTpuShm) reg_tpu_shm_ = nullptr;
  decltype(&TpuServerUnregisterTpuShm) unreg_tpu_shm_ = nullptr;

 private:
  TpuServerLibrary() = default;
  std::mutex mutex_;
  void* handle_ = nullptr;
  std::string bind_error_;
  decltype(&TpuServerNew) new_ = nullptr;
  decltype(&TpuServerDelete) delete_ = nullptr;
  TpuServer* server_ = nullptr;
};

// Result over an in-process response: raw views straight into the engine's
// output arrays (held alive by the response object).
class InferResultCApi : public tpuclient::InferResult {
 public:
  InferResultCApi(TpuServerResponse* response, JsonPtr head)
      : response_(response), head_(std::move(head)) {
    auto& lib = TpuServerLibrary::Get();
    size_t n = lib.resp_count_(response_);
    for (size_t i = 0; i < n; ++i) {
      TpuServerTensor t{};
      char* err = lib.resp_output_(response_, i, &t);
      if (err != nullptr) {
        lib.free_(err);
        continue;
      }
      outputs_[t.name] = t;
    }
  }

  ~InferResultCApi() override {
    TpuServerLibrary::Get().resp_delete_(response_);
  }

  Error ModelName(std::string* name) const override {
    return FromHead("model_name", name);
  }
  Error ModelVersion(std::string* version) const override {
    return FromHead("model_version", version);
  }
  Error Id(std::string* id) const override { return FromHead("id", id); }

  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end())
      return Error("output '" + output_name + "' not found");
    shape->assign(it->second.shape, it->second.shape + it->second.dims);
    return Error::Success();
  }

  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end())
      return Error("output '" + output_name + "' not found");
    *datatype = it->second.datatype;
    return Error::Success();
  }

  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end())
      return Error("output '" + output_name + "' not found");
    *buf = static_cast<const uint8_t*>(it->second.data);
    *byte_size = it->second.byte_size;
    return Error::Success();
  }

  Error RequestStatus() const override { return Error::Success(); }
  std::string DebugString() const override {
    return head_ ? head_->Serialize() : "{}";
  }

 private:
  Error FromHead(const char* key, std::string* out) const {
    if (head_ == nullptr) return Error("no response head");
    JsonPtr v = head_->Get(key);
    *out = v && v->IsString() ? v->AsString() : "";
    return Error::Success();
  }

  TpuServerResponse* response_;
  JsonPtr head_;
  std::map<std::string, TpuServerTensor> outputs_;
};

class CApiClientBackend : public ClientBackend {
 public:
  static Error Create(const std::string& lib_path, const std::string& models,
                      const std::string& repo_root,
                      std::unique_ptr<ClientBackend>* backend) {
    Error err = TpuServerLibrary::Get().Init(lib_path, models, repo_root);
    if (!err.IsOk()) return err;
    backend->reset(new CApiClientBackend());
    return Error::Success();
  }

  Error ServerExtensions(std::vector<std::string>* extensions) override {
    extensions->clear();
    return Error::Success();
  }

  Error ModelMetadata(JsonPtr* metadata, const std::string& model_name,
                      const std::string& version) override {
    auto& lib = TpuServerLibrary::Get();
    char* json = nullptr;
    Error err = lib.Wrap(lib.metadata_(lib.server(), model_name.c_str(),
                                       version.c_str(), &json));
    if (!err.IsOk()) return err;
    err = tpuclient::Json::Parse(json, metadata);
    lib.free_(json);
    return err;
  }

  Error ModelConfig(JsonPtr* config, const std::string& model_name,
                    const std::string& version) override {
    auto& lib = TpuServerLibrary::Get();
    char* json = nullptr;
    Error err = lib.Wrap(lib.config_(lib.server(), model_name.c_str(),
                                     version.c_str(), &json));
    if (!err.IsOk()) return err;
    err = tpuclient::Json::Parse(json, config);
    lib.free_(json);
    return err;
  }

  Error Infer(tpuclient::InferResult** result,
              const tpuclient::InferOptions& options,
              const std::vector<tpuclient::InferInput*>& inputs,
              const std::vector<const tpuclient::InferRequestedOutput*>&
                  outputs) override {
    auto& lib = TpuServerLibrary::Get();
    tpuclient::RequestTimers timers;
    timers.Capture(tpuclient::RequestTimers::Kind::REQUEST_START);
    timers.Capture(tpuclient::RequestTimers::Kind::SEND_START);

    // Build the request head.
    JsonPtr req = tpuclient::Json::MakeObject();
    req->Set("model_name", options.model_name);
    if (!options.model_version.empty())
      req->Set("model_version", options.model_version);
    if (!options.request_id.empty()) req->Set("id", options.request_id);
    if (options.sequence_id != 0) {
      req->Set("sequence_id", uint64_t(options.sequence_id));
      req->Set("sequence_start", options.sequence_start);
      req->Set("sequence_end", options.sequence_end);
    }
    if (options.priority != 0) req->Set("priority", uint64_t(options.priority));
    if (options.server_timeout_us != 0)
      req->Set("timeout_us", uint64_t(options.server_timeout_us));

    std::vector<TpuServerTensor> tensors(inputs.size());
    std::vector<std::string> staging(inputs.size());
    JsonPtr in_list = tpuclient::Json::MakeArray();
    for (size_t i = 0; i < inputs.size(); ++i) {
      const auto* input = inputs[i];
      JsonPtr meta = tpuclient::Json::MakeObject();
      meta->Set("name", input->Name());
      meta->Set("datatype", input->Datatype());
      JsonPtr shape = tpuclient::Json::MakeArray();
      for (int64_t d : input->Shape())
        shape->Append(tpuclient::Json::MakeInt(d));
      meta->Set("shape", shape);
      in_list->Append(meta);

      TpuServerTensor& t = tensors[i];
      t.name = nullptr;  // names travel in the JSON head
      t.datatype = nullptr;
      t.shape = nullptr;
      t.dims = 0;
      if (input->IsSharedMemory()) {
        // Region-referenced input: no bytes cross the boundary; the engine
        // reads from the registered region (data=NULL marks it).
        JsonPtr params = tpuclient::Json::MakeObject();
        params->Set("shared_memory_region", input->SharedMemoryName());
        params->Set("shared_memory_offset",
                    uint64_t(input->SharedMemoryOffset()));
        params->Set("shared_memory_byte_size",
                    uint64_t(input->SharedMemoryByteSize()));
        meta->Set("parameters", params);
        t.data = nullptr;
        t.byte_size = 0;
        continue;
      }
      const auto& bufs = input->Buffers();
      if (bufs.size() == 1) {
        t.data = bufs[0].first;
        t.byte_size = bufs[0].second;
      } else {
        input->CopyTo(&staging[i]);
        t.data = staging[i].data();
        t.byte_size = staging[i].size();
      }
    }
    req->Set("inputs", in_list);
    JsonPtr out_list = tpuclient::Json::MakeArray();
    for (const auto* output : outputs) {
      JsonPtr meta = tpuclient::Json::MakeObject();
      meta->Set("name", output->Name());
      if (output->ClassCount() > 0)
        meta->Set("classification", uint64_t(output->ClassCount()));
      if (output->IsSharedMemory()) {
        JsonPtr params = tpuclient::Json::MakeObject();
        params->Set("shared_memory_region", output->SharedMemoryName());
        params->Set("shared_memory_offset",
                    uint64_t(output->SharedMemoryOffset()));
        params->Set("shared_memory_byte_size",
                    uint64_t(output->SharedMemoryByteSize()));
        meta->Set("parameters", params);
      }
      out_list->Append(meta);
    }
    req->Set("outputs", out_list);

    TpuServerResponse* response = nullptr;
    Error err = lib.Wrap(lib.infer_(lib.server(), req->Serialize().c_str(),
                                    tensors.data(), tensors.size(),
                                    &response));
    timers.Capture(tpuclient::RequestTimers::Kind::SEND_END);
    timers.Capture(tpuclient::RequestTimers::Kind::RECV_START);
    timers.Capture(tpuclient::RequestTimers::Kind::RECV_END);
    timers.Capture(tpuclient::RequestTimers::Kind::REQUEST_END);
    if (!err.IsOk()) return err;

    JsonPtr head;
    Error perr = tpuclient::Json::Parse(lib.resp_json_(response), &head);
    if (!perr.IsOk()) head = nullptr;
    *result = new InferResultCApi(response, head);
    {
      std::lock_guard<std::mutex> lk(stat_mutex_);
      stat_.completed_request_count++;
      stat_.cumulative_total_request_time_ns +=
          timers.request_end_ns - timers.request_start_ns;
    }
    return Error::Success();
  }

  Error AsyncInfer(tpuclient::OnCompleteFn,
                   const tpuclient::InferOptions&,
                   const std::vector<tpuclient::InferInput*>&,
                   const std::vector<const tpuclient::InferRequestedOutput*>&)
      override {
    return Error("TPU_CAPI backend is sync-only (like the reference C-API "
                 "kind)", 400);
  }

  Error ModelInferenceStatistics(std::map<std::string, ModelStatistics>* stats,
                                 const std::string& model_name) override {
    auto& lib = TpuServerLibrary::Get();
    char* json = nullptr;
    Error err =
        lib.Wrap(lib.stats_(lib.server(), model_name.c_str(), &json));
    if (!err.IsOk()) return err;
    JsonPtr body;
    err = tpuclient::Json::Parse(json, &body);
    lib.free_(json);
    if (!err.IsOk()) return err;
    return ParseModelStatsJson(body, stats);
  }

  Error ClientInferStat(tpuclient::InferStat* stat) override {
    std::lock_guard<std::mutex> lk(stat_mutex_);
    *stat = stat_;
    return Error::Success();
  }

  Error RegisterSystemSharedMemory(const std::string& name,
                                   const std::string& key,
                                   size_t byte_size) override {
    auto& lib = TpuServerLibrary::Get();
    return lib.Wrap(lib.reg_sys_shm_(lib.server(), name.c_str(), key.c_str(),
                                     byte_size));
  }

  Error UnregisterSystemSharedMemory(const std::string& name) override {
    auto& lib = TpuServerLibrary::Get();
    return lib.Wrap(lib.unreg_sys_shm_(lib.server(), name.c_str()));
  }

  Error RegisterTpuSharedMemory(const std::string& name,
                                const std::string& raw_handle,
                                int64_t device_id,
                                size_t byte_size) override {
    auto& lib = TpuServerLibrary::Get();
    return lib.Wrap(lib.reg_tpu_shm_(lib.server(), name.c_str(),
                                     raw_handle.data(), raw_handle.size(),
                                     device_id, byte_size));
  }

  Error UnregisterTpuSharedMemory(const std::string& name) override {
    auto& lib = TpuServerLibrary::Get();
    return lib.Wrap(lib.unreg_tpu_shm_(lib.server(), name.c_str()));
  }

  bool SupportsAsync() const override { return false; }

 private:
  CApiClientBackend() = default;
  std::mutex stat_mutex_;
  tpuclient::InferStat stat_;
};

}  // namespace

Error CreateCApiBackend(const std::string& lib_path, const std::string& models,
                        const std::string& repo_root,
                        std::unique_ptr<ClientBackend>* backend) {
  return CApiClientBackend::Create(lib_path, models, repo_root, backend);
}

}  // namespace tpuperf
