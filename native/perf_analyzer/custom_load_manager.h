// Replays a user-supplied file of inter-request intervals.
//
// Counterpart of the reference's custom_load_manager.{h,cc}
// (/root/reference/src/c++/perf_analyzer/custom_load_manager.cc:82-103):
// reads nanosecond intervals (one per line), builds the schedule from them
// instead of a statistical distribution, and reuses the RequestRateManager
// worker machinery.
#pragma once

#include "request_rate_manager.h"

namespace tpuperf {

class CustomLoadManager : public RequestRateManager {
 public:
  static tpuclient::Error Create(const LoadOptions& options,
                                 const std::string& intervals_file,
                                 const ClientBackendFactory& factory,
                                 std::shared_ptr<ModelParser> parser,
                                 std::shared_ptr<DataLoader> data_loader,
                                 std::unique_ptr<CustomLoadManager>* manager);

  tpuclient::Error InitCustomIntervals();
  // Average rate implied by the interval file (drives the profiler's
  // reporting; reference GetCustomRequestRate).
  tpuclient::Error GetCustomRequestRate(double* request_rate);
  tpuclient::Error Start();

 private:
  CustomLoadManager(const LoadOptions& options,
                    const std::string& intervals_file,
                    const ClientBackendFactory& factory,
                    std::shared_ptr<ModelParser> parser,
                    std::shared_ptr<DataLoader> data_loader)
      : RequestRateManager(options, Distribution::CUSTOM, factory,
                           std::move(parser), std::move(data_loader)),
        intervals_file_(intervals_file) {}

  tpuclient::Error GenerateSchedule(double request_rate) override;

  std::string intervals_file_;
  std::vector<uint64_t> intervals_ns_;
};

}  // namespace tpuperf
