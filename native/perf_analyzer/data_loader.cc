#include "data_loader.h"

#include "tpuclient/base64.h"

#include <fstream>
#include <random>
#include <sstream>

#include "tpuclient/common.h"

using tpuclient::Error;
using tpuclient::Json;
using tpuclient::JsonPtr;

namespace tpuperf {

static Error ResolveShape(const ModelTensor& tensor,
                          const DataLoader::Options& opts,
                          std::vector<int64_t>* shape) {
  auto it = opts.shapes.find(tensor.name);
  if (it != opts.shapes.end()) {
    *shape = it->second;
    return Error::Success();
  }
  *shape = tensor.shape;
  for (int64_t& d : *shape) {
    if (d < 0) {
      return Error("input '" + tensor.name +
                       "' has dynamic shape; use --shape to fix it",
                   400);
    }
  }
  return Error::Success();
}

// Raw little-endian bytes for a fixed shape must match exactly — a wrong
// byte count is a load-time error, never a silent truncation.
static Error ValidateRawByteSize(const ModelTensor& tensor,
                                 const std::vector<int64_t>& shape,
                                 size_t byte_size, const std::string& what) {
  int64_t want = tpuclient::ElementCount(shape);
  size_t elem = tpuclient::DtypeByteSize(tensor.datatype);
  if (tensor.datatype != "BYTES" && want >= 0 && elem > 0 &&
      static_cast<size_t>(want) * elem != byte_size) {
    return Error(what + " is " + std::to_string(byte_size) +
                     "B, shape wants " + std::to_string(want * int64_t(elem)) +
                     "B",
                 400);
  }
  return Error::Success();
}

Error DataLoader::MakeTensor(const ModelTensor& tensor, const Options& opts,
                             uint64_t salt, TensorData* out) {
  Error err = ResolveShape(tensor, opts, &out->shape);
  if (!err.IsOk()) return err;
  int64_t elements = tpuclient::ElementCount(out->shape);

  if (tensor.datatype == "BYTES") {
    std::vector<std::string> strings;
    strings.reserve(elements);
    std::mt19937_64 gen(opts.seed + salt);
    std::uniform_int_distribution<int> ch('a', 'z');
    for (int64_t i = 0; i < elements; ++i) {
      if (!opts.string_data.empty()) {
        strings.push_back(opts.string_data);
      } else if (opts.zero_data) {
        strings.emplace_back(opts.string_length, '0');
      } else {
        std::string s(opts.string_length, 'x');
        for (auto& c : s) c = static_cast<char>(ch(gen));
        strings.push_back(std::move(s));
      }
    }
    tpuclient::SerializeStringTensor(strings, &out->bytes);
    return Error::Success();
  }

  size_t elem_size = tpuclient::DtypeByteSize(tensor.datatype);
  if (elem_size == 0) {
    return Error("unsupported datatype '" + tensor.datatype + "' for input '" +
                     tensor.name + "'",
                 400);
  }
  out->bytes.assign(elements * elem_size, '\0');
  if (!opts.zero_data) {
    // Random bytes are fine for every dtype except floats, where random bit
    // patterns can be NaN/inf; fill those from a bounded real distribution.
    std::mt19937_64 gen(opts.seed + salt);
    if (tensor.datatype == "FP32") {
      std::uniform_real_distribution<float> d(0.0f, 1.0f);
      auto* p = reinterpret_cast<float*>(&out->bytes[0]);
      for (int64_t i = 0; i < elements; ++i) p[i] = d(gen);
    } else if (tensor.datatype == "FP64") {
      std::uniform_real_distribution<double> d(0.0, 1.0);
      auto* p = reinterpret_cast<double*>(&out->bytes[0]);
      for (int64_t i = 0; i < elements; ++i) p[i] = d(gen);
    } else if (tensor.datatype == "FP16" || tensor.datatype == "BF16") {
      // positive small half/bfloat patterns: zero exponent-high bits kept
      std::uniform_int_distribution<uint16_t> d(0, 0x3BFF);
      auto* p = reinterpret_cast<uint16_t*>(&out->bytes[0]);
      for (int64_t i = 0; i < elements; ++i) p[i] = d(gen);
    } else {
      std::uniform_int_distribution<int> d(0, 127);
      for (auto& c : out->bytes) c = static_cast<char>(d(gen));
    }
  }
  return Error::Success();
}

Error DataLoader::GenerateData(const ModelParser& parser,
                               const Options& opts) {
  data_.clear();
  data_.emplace_back();
  data_[0].emplace_back();
  uint64_t salt = 0;
  for (const auto& kv : parser.Inputs()) {
    TensorData td;
    Error err = MakeTensor(kv.second, opts, salt++, &td);
    if (!err.IsOk()) return err;
    data_[0][0][kv.first] = std::move(td);
  }
  return Error::Success();
}

// One JSON step object {input_name: value} -> wire tensors. Value forms:
// flat array, nested array (shape inferred), {"content": [...],
// "shape": [...]}, or {"b64": "..."} (base64-encoded raw little-endian
// bytes, the reference's binary JSON form).
static Error ParseStep(const ModelParser& parser, const JsonPtr& step_obj,
                       const DataLoader::Options& opts,
                       std::map<std::string, std::string>* raw,
                       std::map<std::string, std::vector<int64_t>>* shapes);

static void FlattenJsonArray(const JsonPtr& v, std::vector<JsonPtr>* out,
                             std::vector<int64_t>* shape, int depth) {
  if (v->IsArray()) {
    if (static_cast<int>(shape->size()) <= depth)
      shape->push_back(static_cast<int64_t>(v->Size()));
    for (size_t i = 0; i < v->Size(); ++i)
      FlattenJsonArray(v->At(i), out, shape, depth + 1);
  } else {
    out->push_back(v);
  }
}

static Error EncodeScalars(const ModelTensor& tensor,
                           const std::vector<JsonPtr>& scalars,
                           std::string* bytes) {
  if (tensor.datatype == "BYTES") {
    std::vector<std::string> strings;
    strings.reserve(scalars.size());
    for (const auto& s : scalars) {
      if (!s->IsString())
        return Error("BYTES input '" + tensor.name + "' needs strings", 400);
      strings.push_back(s->AsString());
    }
    tpuclient::SerializeStringTensor(strings, bytes);
    return Error::Success();
  }
  size_t elem_size = tpuclient::DtypeByteSize(tensor.datatype);
  bytes->assign(scalars.size() * elem_size, '\0');
  for (size_t i = 0; i < scalars.size(); ++i) {
    char* dst = &(*bytes)[i * elem_size];
    const std::string& dt = tensor.datatype;
    if (dt == "FP32") {
      float v = static_cast<float>(scalars[i]->AsDouble());
      memcpy(dst, &v, 4);
    } else if (dt == "FP64") {
      double v = scalars[i]->AsDouble();
      memcpy(dst, &v, 8);
    } else if (dt == "INT64") {
      int64_t v = scalars[i]->AsInt();
      memcpy(dst, &v, 8);
    } else if (dt == "UINT64") {
      uint64_t v = scalars[i]->AsUint();
      memcpy(dst, &v, 8);
    } else if (dt == "INT32") {
      int32_t v = static_cast<int32_t>(scalars[i]->AsInt());
      memcpy(dst, &v, 4);
    } else if (dt == "UINT32") {
      uint32_t v = static_cast<uint32_t>(scalars[i]->AsUint());
      memcpy(dst, &v, 4);
    } else if (dt == "INT16") {
      int16_t v = static_cast<int16_t>(scalars[i]->AsInt());
      memcpy(dst, &v, 2);
    } else if (dt == "UINT16") {
      uint16_t v = static_cast<uint16_t>(scalars[i]->AsUint());
      memcpy(dst, &v, 2);
    } else if (dt == "INT8") {
      *dst = static_cast<char>(scalars[i]->AsInt());
    } else if (dt == "UINT8") {
      *reinterpret_cast<uint8_t*>(dst) =
          static_cast<uint8_t>(scalars[i]->AsUint());
    } else if (dt == "BOOL") {
      *dst = scalars[i]->AsBool() ? 1 : 0;
    } else {
      return Error("unsupported datatype '" + dt + "' in JSON data", 400);
    }
  }
  return Error::Success();
}

static Error ParseStep(const ModelParser& parser, const JsonPtr& step_obj,
                       const DataLoader::Options& opts,
                       std::map<std::string, std::string>* raw,
                       std::map<std::string, std::vector<int64_t>>* shapes) {
  if (!step_obj->IsObject()) return Error("data step must be an object", 400);
  for (const auto& member : step_obj->Members()) {
    const std::string& name = member.first;
    auto it = parser.Inputs().find(name);
    if (it == parser.Inputs().end())
      return Error("data file names unknown input '" + name + "'", 400);
    const ModelTensor& tensor = it->second;

    JsonPtr value = member.second;
    std::vector<int64_t> shape;
    JsonPtr content = value;
    if (value->IsObject()) {
      JsonPtr sh = value->Get("shape");
      if (sh && sh->IsArray()) {
        for (size_t i = 0; i < sh->Size(); ++i)
          shape.push_back(sh->At(i)->AsInt());
      }
      // {"b64": "..."}: raw little-endian tensor bytes, base64-encoded
      // (reference data_loader.cc binary content form).
      JsonPtr b64 = value->Get("b64");
      if (b64 && b64->IsString()) {
        std::vector<uint8_t> decoded;
        if (!tpuclient::Base64Decode(b64->AsString(), &decoded))
          return Error("invalid b64 content for input '" + name + "'", 400);
        if (shape.empty()) {
          Error err = ResolveShape(tensor, opts, &shape);
          if (!err.IsOk()) return err;
        }
        Error verr = ValidateRawByteSize(tensor, shape, decoded.size(),
                                         "b64 data for '" + name + "'");
        if (!verr.IsOk()) return verr;
        (*raw)[name] = std::string(decoded.begin(), decoded.end());
        (*shapes)[name] = std::move(shape);
        continue;
      }
      content = value->Get("content");
      if (!content) return Error("data object missing 'content'", 400);
    }
    std::vector<JsonPtr> scalars;
    std::vector<int64_t> inferred;
    FlattenJsonArray(content, &scalars, &inferred, 0);
    if (shape.empty()) {
      // flat arrays take the declared (or overridden) model shape
      if (inferred.size() <= 1) {
        Error err = ResolveShape(tensor, opts, &shape);
        if (!err.IsOk()) shape = {static_cast<int64_t>(scalars.size())};
      } else {
        shape = inferred;
      }
    }
    int64_t want = tpuclient::ElementCount(shape);
    if (want >= 0 && want != static_cast<int64_t>(scalars.size())) {
      return Error("data for '" + name + "' has " +
                       std::to_string(scalars.size()) +
                       " elements, shape wants " + std::to_string(want),
                   400);
    }
    std::string bytes;
    Error err = EncodeScalars(tensor, scalars, &bytes);
    if (!err.IsOk()) return err;
    (*raw)[name] = std::move(bytes);
    (*shapes)[name] = std::move(shape);
  }
  return Error::Success();
}

Error DataLoader::ReadDataFromJson(const ModelParser& parser,
                                   const std::string& path,
                                   const Options& opts) {
  std::ifstream f(path);
  if (!f.good()) return Error("cannot open data file '" + path + "'", 400);
  std::stringstream ss;
  ss << f.rdbuf();
  JsonPtr root;
  Error err = Json::Parse(ss.str(), &root);
  if (!err.IsOk()) return err;
  if (!root->IsObject() || !root->Has("data"))
    return Error("data file must be {\"data\": [...]}", 400);
  JsonPtr data = root->Get("data");
  if (!data->IsArray() || data->Size() == 0)
    return Error("'data' must be a non-empty array", 400);

  data_.clear();
  bool stream_major = data->At(0)->IsArray();
  size_t n_streams = stream_major ? data->Size() : 1;
  for (size_t s = 0; s < n_streams; ++s) {
    data_.emplace_back();
    JsonPtr steps = stream_major ? data->At(s) : data;
    for (size_t st = 0; st < steps->Size(); ++st) {
      std::map<std::string, std::string> raw;
      std::map<std::string, std::vector<int64_t>> shapes;
      err = ParseStep(parser, steps->At(st), opts, &raw, &shapes);
      if (!err.IsOk()) return err;
      data_[s].emplace_back();
      for (auto& kv : raw) {
        TensorData td;
        td.bytes = std::move(kv.second);
        td.shape = shapes[kv.first];
        data_[s].back()[kv.first] = std::move(td);
      }
    }
  }
  return Error::Success();
}

Error DataLoader::ReadDataFromDir(const ModelParser& parser,
                                  const std::string& dir,
                                  const Options& opts) {
  data_.clear();
  data_.emplace_back();
  data_[0].emplace_back();
  for (const auto& kv : parser.Inputs()) {
    const ModelTensor& tensor = kv.second;
    const std::string path = dir + "/" + tensor.name;
    std::ifstream f(path, std::ios::binary);
    if (!f.good())
      return Error("cannot open data file '" + path + "' for input '" +
                       tensor.name + "'",
                   400);
    TensorData td;
    Error err = ResolveShape(tensor, opts, &td.shape);
    if (!err.IsOk()) return err;
    if (tensor.datatype == "BYTES") {
      std::vector<std::string> lines;
      std::string line;
      while (std::getline(f, line)) {
        if (!line.empty() && line.back() == '\r') line.pop_back();
        lines.push_back(line);
      }
      int64_t want = tpuclient::ElementCount(td.shape);
      if (want >= 0 && want != static_cast<int64_t>(lines.size())) {
        return Error("file '" + path + "' has " +
                         std::to_string(lines.size()) +
                         " lines, shape wants " + std::to_string(want) +
                         " strings",
                     400);
      }
      tpuclient::SerializeStringTensor(lines, &td.bytes);
    } else {
      std::stringstream ss;
      ss << f.rdbuf();
      td.bytes = ss.str();
      Error verr = ValidateRawByteSize(tensor, td.shape, td.bytes.size(),
                                       "file '" + path + "'");
      if (!verr.IsOk()) return verr;
    }
    data_[0][0][kv.first] = std::move(td);
  }
  return Error::Success();
}

Error DataLoader::GetInputData(const std::string& name, size_t stream,
                               size_t step, const uint8_t** data,
                               size_t* byte_size,
                               std::vector<int64_t>* shape) const {
  if (stream >= data_.size() || step >= data_[stream].size())
    return Error("no data for stream " + std::to_string(stream) + " step " +
                     std::to_string(step),
                 400);
  auto it = data_[stream][step].find(name);
  if (it == data_[stream][step].end())
    return Error("no data for input '" + name + "'", 400);
  *data = reinterpret_cast<const uint8_t*>(it->second.bytes.data());
  *byte_size = it->second.bytes.size();
  if (shape != nullptr) *shape = it->second.shape;
  return Error::Success();
}

}  // namespace tpuperf
