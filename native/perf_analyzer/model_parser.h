// Model metadata/config -> harness scheduling knowledge.
//
// Counterpart of the reference's model_parser.{h,cc}
// (/root/reference/src/c++/perf_analyzer/model_parser.h:33-149): classifies
// the model's scheduler (NONE / DYNAMIC / SEQUENCE / ENSEMBLE /
// ENSEMBLE_SEQUENCE), records batching capability and tensor shapes, and
// collects composing-model names for ensemble stat rollups.
#pragma once

#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "tpuclient/error.h"
#include "tpuclient/json.h"

namespace tpuperf {

struct ModelTensor {
  std::string name;
  std::string datatype;           // v2 wire dtype ("INT32", "BYTES", ...)
  std::vector<int64_t> shape;     // without batch dim; -1 = dynamic
  bool is_optional = false;
};

class ModelParser {
 public:
  enum class SchedulerType {
    NONE,
    DYNAMIC,
    SEQUENCE,
    ENSEMBLE,
    ENSEMBLE_SEQUENCE
  };

  // metadata: GET /v2/models/<m> JSON; config: GET /v2/models/<m>/config.
  tpuclient::Error Init(const tpuclient::JsonPtr& metadata,
                        const tpuclient::JsonPtr& config);

  const std::string& Name() const { return name_; }
  const std::string& Version() const { return version_; }
  SchedulerType Scheduler() const { return scheduler_; }
  int64_t MaxBatchSize() const { return max_batch_size_; }
  bool IsDecoupled() const { return decoupled_; }
  const std::map<std::string, ModelTensor>& Inputs() const { return inputs_; }
  const std::map<std::string, ModelTensor>& Outputs() const {
    return outputs_;
  }
  // Composing models of an ensemble (for per-model stat rollup, reference
  // inference_profiler.cc:910-960).
  const std::set<std::string>& ComposingModels() const { return composing_; }

 private:
  std::string name_;
  std::string version_;
  SchedulerType scheduler_ = SchedulerType::NONE;
  int64_t max_batch_size_ = 0;
  bool decoupled_ = false;
  std::map<std::string, ModelTensor> inputs_;
  std::map<std::string, ModelTensor> outputs_;
  std::set<std::string> composing_;
};

}  // namespace tpuperf
