// In-process TPU serving engine C API — implementation.
//
// Embeds CPython once per process and hosts the JAX/XLA engine through
// client_tpu/capi_embed.py; every exported function is a thin marshalling
// layer (GIL acquire -> PyObject calls -> release). Inputs enter as
// zero-copy memoryviews; outputs leave as buffer-protocol views pinned by
// the response object. See tpu_server_capi.h for the contract and the
// reference-role citation.

#include "tpu_server_capi.h"

#define PY_SSIZE_T_CLEAN  // '#' length args are Py_ssize_t (required 3.12+)
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::once_flag g_init_once;
PyObject* g_embed_module = nullptr;  // client_tpu.capi_embed
std::string g_init_error;

char* DupString(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

// Formats the current Python exception into an error string (clears it).
std::string FetchPyError() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* str = PyObject_Str(value);
    if (str != nullptr) {
      const char* c = PyUnicode_AsUTF8(str);
      if (c != nullptr) msg = c;
      Py_DECREF(str);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

void InitializePython(const char* repo_root) {
  bool did_initialize = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    did_initialize = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* sys_path = PySys_GetObject("path");  // borrowed
  auto prepend = [sys_path](const char* p) {
    if (p == nullptr || *p == '\0' || sys_path == nullptr) return;
    PyObject* s = PyUnicode_FromString(p);
    if (s != nullptr) {
      PyList_Insert(sys_path, 0, s);
      Py_DECREF(s);
    }
  };
  prepend(".");
  prepend(getenv("TPU_REPO_ROOT"));
  prepend(repo_root);
  g_embed_module = PyImport_ImportModule("client_tpu.capi_embed");
  if (g_embed_module == nullptr) {
    g_init_error = "failed to import client_tpu.capi_embed: " + FetchPyError();
  }
  PyGILState_Release(gil);
  // Only when THIS code booted the interpreter does the thread still hold
  // the GIL (from Py_InitializeEx) — release it so worker threads can use
  // PyGILState_Ensure. When loaded into an already-running Python process
  // (ctypes), the caller owns the GIL and it must be left alone.
  if (did_initialize && PyGILState_Check()) {
    PyEval_SaveThread();
  }
}

// Calls g_embed_module.<fn>(*args); returns new reference or null + error.
PyObject* CallEmbed(const char* fn, PyObject* args, std::string* error) {
  PyObject* callable = PyObject_GetAttrString(g_embed_module, fn);
  if (callable == nullptr) {
    *error = "missing capi_embed." + std::string(fn);
    return nullptr;
  }
  PyObject* result = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  if (result == nullptr) *error = FetchPyError();
  return result;
}

}  // namespace

struct TpuServer {
  PyObject* engine = nullptr;
};

struct TpuServerResponse {
  std::string json;
  // Per output: metadata strings + a buffer-protocol view into the array.
  struct Output {
    std::string name;
    std::string datatype;
    std::vector<int64_t> shape;
    Py_buffer view{};
    bool have_view = false;
  };
  std::vector<Output> outputs;
  PyObject* arrays = nullptr;  // keeps the ndarrays alive
};

extern "C" {

char* TpuServerNew(TpuServer** server, const char* models_csv,
                   const char* repo_root) {
  std::call_once(g_init_once, InitializePython, repo_root);
  if (g_embed_module == nullptr) return DupString(g_init_error);

  PyGILState_STATE gil = PyGILState_Ensure();
  std::string error;
  PyObject* args = Py_BuildValue("(s)", models_csv ? models_csv : "");
  PyObject* engine = CallEmbed("create_engine", args, &error);
  Py_XDECREF(args);
  if (engine == nullptr) {
    PyGILState_Release(gil);
    return DupString("create_engine failed: " + error);
  }
  *server = new TpuServer{engine};
  PyGILState_Release(gil);
  return nullptr;
}

void TpuServerDelete(TpuServer* server) {
  if (server == nullptr) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  std::string error;
  PyObject* args = Py_BuildValue("(O)", server->engine);
  PyObject* r = CallEmbed("shutdown_engine", args, &error);
  Py_XDECREF(args);
  Py_XDECREF(r);
  PyErr_Clear();
  Py_DECREF(server->engine);
  PyGILState_Release(gil);
  delete server;
}

static char* JsonCall(TpuServer* server, const char* fn, const char* a1,
                      const char* a2, char** json_out) {
  PyGILState_STATE gil = PyGILState_Ensure();
  std::string error;
  PyObject* args =
      a2 != nullptr
          ? Py_BuildValue("(Oss)", server->engine, a1 ? a1 : "", a2)
          : (a1 != nullptr ? Py_BuildValue("(Os)", server->engine, a1)
                           : Py_BuildValue("(O)", server->engine));
  PyObject* result = CallEmbed(fn, args, &error);
  Py_XDECREF(args);
  char* err = nullptr;
  if (result == nullptr) {
    err = DupString(error);
  } else {
    const char* c = PyUnicode_AsUTF8(result);
    if (c == nullptr) {
      // Non-string return: PyUnicode_AsUTF8 raised — clear it so the
      // pending exception can't poison the next C-API call on this thread.
      PyErr_Clear();
      err = DupString("embed function returned a non-string result");
    } else {
      *json_out = DupString(c);
    }
    Py_DECREF(result);
  }
  PyGILState_Release(gil);
  return err;
}

char* TpuServerMetadataJson(TpuServer* server, char** json_out) {
  return JsonCall(server, "server_metadata_json", nullptr, nullptr, json_out);
}

char* TpuServerModelMetadataJson(TpuServer* server, const char* model,
                                 const char* version, char** json_out) {
  return JsonCall(server, "model_metadata_json", model, version ? version : "",
                  json_out);
}

char* TpuServerModelConfigJson(TpuServer* server, const char* model,
                               const char* version, char** json_out) {
  return JsonCall(server, "model_config_json", model, version ? version : "",
                  json_out);
}

char* TpuServerModelStatisticsJson(TpuServer* server, const char* model,
                                   char** json_out) {
  return JsonCall(server, "model_statistics_json", model ? model : "", "",
                  json_out);
}

// Shared helper for the shm control-plane calls: fn(engine, ...) -> None.
static char* VoidCall(TpuServer* server, const char* fn, PyObject* args) {
  std::string error;
  PyObject* result = CallEmbed(fn, args, &error);
  Py_XDECREF(args);
  if (result == nullptr) return DupString(error);
  Py_DECREF(result);
  return nullptr;
}

char* TpuServerRegisterSystemShm(TpuServer* server, const char* name,
                                 const char* key, size_t byte_size) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Ossn)", server->engine, name, key,
                                 Py_ssize_t(byte_size));
  char* err = VoidCall(server, "register_system_shm", args);
  PyGILState_Release(gil);
  return err;
}

char* TpuServerUnregisterSystemShm(TpuServer* server, const char* name) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Os)", server->engine, name ? name : "");
  char* err = VoidCall(server, "unregister_system_shm", args);
  PyGILState_Release(gil);
  return err;
}

char* TpuServerRegisterTpuShm(TpuServer* server, const char* name,
                              const void* raw_handle, size_t handle_len,
                              int64_t device_id, size_t byte_size) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_BuildValue(
      "(Osy#Ln)", server->engine, name,
      static_cast<const char*>(raw_handle), Py_ssize_t(handle_len),
      static_cast<long long>(device_id), Py_ssize_t(byte_size));
  char* err = VoidCall(server, "register_tpu_shm", args);
  PyGILState_Release(gil);
  return err;
}

char* TpuServerUnregisterTpuShm(TpuServer* server, const char* name) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Os)", server->engine, name ? name : "");
  char* err = VoidCall(server, "unregister_tpu_shm", args);
  PyGILState_Release(gil);
  return err;
}

char* TpuServerInfer(TpuServer* server, const char* request_json,
                     const TpuServerTensor* inputs, size_t input_count,
                     TpuServerResponse** response) {
  PyGILState_STATE gil = PyGILState_Ensure();
  std::string error;

  PyObject* buffers = PyList_New(Py_ssize_t(input_count));
  for (size_t i = 0; i < input_count; ++i) {
    if (inputs[i].data == nullptr) {
      // shm-referenced input: bytes come from the registered region, the
      // JSON meta carries the shared_memory_* parameters.
      Py_INCREF(Py_None);
      PyList_SET_ITEM(buffers, Py_ssize_t(i), Py_None);
      continue;
    }
    // Zero-copy read-only view of caller memory; valid for this call only
    // (capi_embed._input_array documents the lifetime contract).
    PyObject* mv = PyMemoryView_FromMemory(
        const_cast<char*>(static_cast<const char*>(inputs[i].data)),
        Py_ssize_t(inputs[i].byte_size), PyBUF_READ);
    if (mv == nullptr) {
      Py_DECREF(buffers);
      PyGILState_Release(gil);
      return DupString("failed to wrap input buffer " + std::to_string(i));
    }
    PyList_SET_ITEM(buffers, Py_ssize_t(i), mv);  // steals ref
  }

  PyObject* args = Py_BuildValue("(OsO)", server->engine, request_json,
                                 buffers);
  Py_DECREF(buffers);
  PyObject* result = CallEmbed("infer", args, &error);
  Py_XDECREF(args);
  if (result == nullptr) {
    PyGILState_Release(gil);
    return DupString(error);
  }

  // result = (response_json: str, arrays: list[np.ndarray],
  //           metas: list[(name, datatype, shape)]) — the metadata tuples
  // avoid any JSON parsing on this hot path.
  if (!PyTuple_Check(result) || PyTuple_Size(result) != 3 ||
      !PyList_Check(PyTuple_GetItem(result, 1)) ||
      !PyList_Check(PyTuple_GetItem(result, 2))) {
    Py_DECREF(result);
    PyErr_Clear();
    PyGILState_Release(gil);
    return DupString("capi_embed.infer returned an unexpected shape "
                     "(want (json_str, list, list))");
  }
  PyObject* json_obj = PyTuple_GetItem(result, 0);   // borrowed
  PyObject* arrays = PyTuple_GetItem(result, 1);     // borrowed
  PyObject* metas = PyTuple_GetItem(result, 2);      // borrowed
  auto* resp = new TpuServerResponse();
  const char* jc =
      PyUnicode_Check(json_obj) ? PyUnicode_AsUTF8(json_obj) : nullptr;
  resp->json = jc ? jc : "{}";
  Py_INCREF(arrays);
  resp->arrays = arrays;

  Py_ssize_t n = PyList_Size(arrays);
  for (Py_ssize_t i = 0; i < n; ++i) {
    TpuServerResponse::Output out;
    if (i < PyList_Size(metas)) {
      PyObject* meta = PyList_GetItem(metas, i);  // borrowed
      if (PyTuple_Check(meta) && PyTuple_Size(meta) == 3) {
        PyObject* name = PyTuple_GetItem(meta, 0);
        PyObject* dtype = PyTuple_GetItem(meta, 1);
        PyObject* shape = PyTuple_GetItem(meta, 2);
        const char* nc =
            PyUnicode_Check(name) ? PyUnicode_AsUTF8(name) : nullptr;
        const char* dc =
            PyUnicode_Check(dtype) ? PyUnicode_AsUTF8(dtype) : nullptr;
        if (nc) out.name = nc;
        if (dc) out.datatype = dc;
        if (PyList_Check(shape)) {
          for (Py_ssize_t d = 0; d < PyList_Size(shape); ++d) {
            out.shape.push_back(
                PyLong_AsLongLong(PyList_GetItem(shape, d)));
          }
        }
      }
    }
    PyObject* arr = PyList_GetItem(arrays, i);  // borrowed
    if (PyObject_GetBuffer(arr, &out.view, PyBUF_SIMPLE) == 0) {
      out.have_view = true;
    } else {
      PyErr_Clear();
    }
    resp->outputs.push_back(std::move(out));
  }
  Py_DECREF(result);
  PyGILState_Release(gil);
  *response = resp;
  return nullptr;
}

const char* TpuServerResponseJson(TpuServerResponse* response) {
  return response->json.c_str();
}

size_t TpuServerResponseOutputCount(TpuServerResponse* response) {
  return response->outputs.size();
}

char* TpuServerResponseOutput(TpuServerResponse* response, size_t index,
                              TpuServerTensor* tensor) {
  if (index >= response->outputs.size()) {
    return DupString("output index out of range");
  }
  const auto& out = response->outputs[index];
  tensor->name = out.name.c_str();
  tensor->datatype = out.datatype.c_str();
  tensor->shape = out.shape.data();
  tensor->dims = out.shape.size();
  tensor->data = out.have_view ? out.view.buf : nullptr;
  tensor->byte_size = out.have_view ? size_t(out.view.len) : 0;
  return nullptr;
}

void TpuServerResponseDelete(TpuServerResponse* response) {
  if (response == nullptr) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  for (auto& out : response->outputs) {
    if (out.have_view) PyBuffer_Release(&out.view);
  }
  Py_XDECREF(response->arrays);
  PyGILState_Release(gil);
  delete response;
}

void TpuServerFreeString(char* s) { free(s); }

}  // extern "C"
