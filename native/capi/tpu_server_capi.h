// C API of the in-process TPU serving engine (libtpuserver.so).
//
// Counterpart of the TRITONSERVER_* C API surface the reference dlopens
// (/root/reference/src/c++/perf_analyzer/client_backend/triton_c_api/
// triton_loader.h:83-255): a benchmark process loads this library, creates a
// server bound to the model zoo, and runs inference with zero network in the
// loop. The implementation embeds CPython and hosts the JAX/XLA engine
// (client_tpu.capi_embed); this header is plain C so any language can bind.
//
// Error convention: functions return a malloc'd error string (caller frees
// with TpuServerFreeString) or NULL on success.
#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct TpuServer TpuServer;
typedef struct TpuServerResponse TpuServerResponse;

// A tensor view. For inputs, all pointers are caller-owned and must stay
// valid for the duration of the call. For outputs, pointers are owned by the
// TpuServerResponse and valid until TpuServerResponseDelete.
typedef struct {
  const char* name;
  const char* datatype;   // v2 wire dtype string ("INT32", "FP32", ...)
  const int64_t* shape;
  size_t dims;
  const void* data;
  size_t byte_size;
} TpuServerTensor;

// Creates a server hosting the given comma-separated model-zoo models (empty
// = all). repo_root is prepended to the embedded interpreter's sys.path so
// `client_tpu` resolves; pass NULL to rely on the process CWD.
char* TpuServerNew(TpuServer** server, const char* models_csv,
                   const char* repo_root);
void TpuServerDelete(TpuServer* server);

// Control plane: JSON results (v2-shaped dicts), caller frees *json_out
// with TpuServerFreeString.
char* TpuServerMetadataJson(TpuServer* server, char** json_out);
char* TpuServerModelMetadataJson(TpuServer* server, const char* model,
                                 const char* version, char** json_out);
char* TpuServerModelConfigJson(TpuServer* server, const char* model,
                               const char* version, char** json_out);
char* TpuServerModelStatisticsJson(TpuServer* server, const char* model,
                                   char** json_out);

// Shared-memory control plane: the in-process analogs of the network
// Register*SharedMemory RPCs, so a perf harness can exercise the shm data
// planes with zero network. raw_handle carries the serialized TPU region
// handle bytes (same schema the gRPC/HTTP register calls transport).
char* TpuServerRegisterSystemShm(TpuServer* server, const char* name,
                                 const char* key, size_t byte_size);
char* TpuServerUnregisterSystemShm(TpuServer* server, const char* name);
char* TpuServerRegisterTpuShm(TpuServer* server, const char* name,
                              const void* raw_handle, size_t handle_len,
                              int64_t device_id, size_t byte_size);
char* TpuServerUnregisterTpuShm(TpuServer* server, const char* name);

// Synchronous inference. request_json carries model/id/sequence options and
// the input/output descriptors:
//   {"model_name": ..., "id": ..., "sequence_id": ..., ...,
//    "inputs": [{"name","datatype","shape", "parameters": {...}}...],
//    "outputs": [{"name","classification","parameters": {...}}...]}
// inputs[i].data supplies the raw bytes for request_json["inputs"][i]; an
// input whose parameters name a shared_memory_region passes data=NULL and
// the engine reads the bytes from the registered region (outputs
// symmetrically write into their region and return no data view).
char* TpuServerInfer(TpuServer* server, const char* request_json,
                     const TpuServerTensor* inputs, size_t input_count,
                     TpuServerResponse** response);

// Response access: header JSON (model/id/output metadata) plus zero-copy
// tensor views into the engine's output arrays.
const char* TpuServerResponseJson(TpuServerResponse* response);
size_t TpuServerResponseOutputCount(TpuServerResponse* response);
char* TpuServerResponseOutput(TpuServerResponse* response, size_t index,
                              TpuServerTensor* tensor);
void TpuServerResponseDelete(TpuServerResponse* response);

void TpuServerFreeString(char* s);

#ifdef __cplusplus
}
#endif
