"""Deprecated alias for :mod:`client_tpu.grpc`.

Compat-shim pattern of the reference's tritongrpcclient module
(tritongrpcclient/__init__.py:28-36).
"""

import warnings

from client_tpu.grpc import *  # noqa: F401,F403
from client_tpu.grpc import InferenceServerClient, InferInput, \
    InferRequestedOutput, InferResult  # noqa: F401

warnings.warn(
    "tpugrpcclient is deprecated; import client_tpu.grpc instead",
    DeprecationWarning, stacklevel=2)
