"""Deprecated alias for :mod:`client_tpu.http`.

Compat-shim pattern of the reference's tritonhttpclient module
(/root/reference/src/python/library/tritonhttpclient/__init__.py:28-36:
DeprecationWarning + star re-export).
"""

import warnings

from client_tpu.http import *  # noqa: F401,F403
from client_tpu.http import InferenceServerClient, InferInput, \
    InferRequestedOutput, InferResult  # noqa: F401

warnings.warn(
    "tpuhttpclient is deprecated; import client_tpu.http instead",
    DeprecationWarning, stacklevel=2)
