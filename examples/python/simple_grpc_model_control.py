#!/usr/bin/env python3
"""Explicit model control over gRPC: unload then load a model, checking
readiness transitions and the repository index.

Reference counterpart: src/python/examples/simple_grpc_model_control.py.
"""

import argparse
import sys

from client_tpu.grpc import InferenceServerClient

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-m", "--model", default="simple")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    if not client.is_model_ready(args.model):
        client.load_model(args.model)
    assert client.is_model_ready(args.model)

    client.unload_model(args.model)
    if client.is_model_ready(args.model):
        sys.exit("error: model still ready after unload")

    index = client.get_model_repository_index()
    names = [m.name for m in index.models]
    if args.model not in names:
        sys.exit(f"error: {args.model} missing from repository index")

    client.load_model(args.model)
    if not client.is_model_ready(args.model):
        sys.exit("error: model not ready after load")

print("PASS: model control (grpc)")
