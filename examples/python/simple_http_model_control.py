#!/usr/bin/env python3
"""Explicit model control over HTTP: unload then load a model, checking
readiness transitions.

Reference counterpart: src/python/examples/simple_http_model_control.py
(load/unload/ready flow, grpc variant identical in spirit).
"""

import argparse
import sys

from client_tpu.http import InferenceServerClient

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
parser.add_argument("-m", "--model", default="simple")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    if not client.is_model_ready(args.model):
        client.load_model(args.model)
    assert client.is_model_ready(args.model)

    client.unload_model(args.model)
    if client.is_model_ready(args.model):
        sys.exit("error: model still ready after unload")

    client.load_model(args.model)
    if not client.is_model_ready(args.model):
        sys.exit("error: model not ready after load")

print("PASS: model control")
