#!/usr/bin/env python3
"""Raw-stub client using *explicit* typed contents (int_contents) instead of
raw_input_contents — the other legal wire form for tensor data.

Reference counterpart: grpc_explicit_int_content_client.py
(/root/reference/src/python/examples/): generated-stub usage, INT32 tensors
through InferTensorContents.int_contents on the `simple` model.
"""

import argparse
import sys

import grpc
import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

channel = grpc.insecure_channel(args.url)
stub = GRPCInferenceServiceStub(channel)

request = pb.ModelInferRequest(model_name="simple", id="explicit-int")
in0 = np.arange(16, dtype=np.int32)
in1 = np.full(16, 5, dtype=np.int32)
for name, arr in (("INPUT0", in0), ("INPUT1", in1)):
    t = request.inputs.add(name=name, datatype="INT32", shape=[1, 16])
    t.contents.int_contents.extend(arr.tolist())
request.outputs.add(name="OUTPUT0")
request.outputs.add(name="OUTPUT1")

response = stub.ModelInfer(request)

# Explicit-content requests come back as raw_output_contents by default.
outputs = {}
for tensor, raw in zip(response.outputs, response.raw_output_contents):
    outputs[tensor.name] = np.frombuffer(raw, np.int32)
if not np.array_equal(outputs["OUTPUT0"], in0 + in1):
    sys.exit(f"error: bad sum {outputs['OUTPUT0']}")
if not np.array_equal(outputs["OUTPUT1"], in0 - in1):
    sys.exit(f"error: bad difference {outputs['OUTPUT1']}")

print("PASS: explicit int content")
