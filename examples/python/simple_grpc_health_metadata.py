#!/usr/bin/env python3
"""Health and metadata control plane over gRPC: live/ready, model ready,
server and model metadata, model config.

Reference counterpart: src/python/examples/simple_grpc_health_metadata.py.
"""

import argparse
import sys

from client_tpu.grpc import InferenceServerClient

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-m", "--model", default="simple")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    if not client.is_server_live():
        sys.exit("error: server not live")
    if not client.is_server_ready():
        sys.exit("error: server not ready")
    if not client.is_model_ready(args.model):
        sys.exit(f"error: model {args.model} not ready")

    meta = client.get_server_metadata()
    print(f"server: {meta.name} {meta.version}")

    model_meta = client.get_model_metadata(args.model)
    if model_meta.name != args.model:
        sys.exit("error: model metadata name mismatch")
    print(f"model inputs: {[t.name for t in model_meta.inputs]}")

    config = client.get_model_config(args.model)
    if config.config.name != args.model:
        sys.exit("error: model config name mismatch")

    stats = client.get_inference_statistics(args.model)
    print(f"model stats entries: {len(stats.model_stats)}")

print("PASS: health metadata (grpc)")
