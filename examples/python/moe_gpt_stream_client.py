#!/usr/bin/env python3
"""Token streaming from the expert-parallel generative family
(`moe_gpt_mc`) over the gRPC bidi stream, with response coalescing.

Two framework features in one client, both invisible at the wire level
beyond what this script shows:

- the server decodes through the continuous-batching arena with a
  Switch-MoE FFN inside every wave (experts sharded over the mesh's
  ``ep`` axis — dropless routing, so this stream is bit-identical no
  matter what else is co-batched);
- ``response_coalesce`` lets a backlogged server merge several tokens
  into one ``[k]``-shaped message — the client below handles 1- and
  k-token messages identically by iterating the TOKEN tensor.

Extends the reference's decoupled-stream contract
(/root/reference/src/python/examples/simple_grpc_custom_repeat.py):
``triton_final_response`` terminates the request.

Serve with: python -m client_tpu.server --zoo moe_gpt_mc
"""

import argparse
import sys
import threading

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-n", "--max-tokens", type=int, default=12)
parser.add_argument("-v", "--verbose", action="store_true")
args = parser.parse_args()

tokens: list[int] = []
errors: list[str] = []
done = threading.Event()


def callback(result, error):
    if error is not None:
        errors.append(str(error))
        done.set()
        return
    response = result.get_response()
    if response.outputs:
        toks = result.as_numpy("TOKEN")
        idx = result.as_numpy("INDEX")
        for i, t in zip(idx, toks):
            # report (not assert): the stream reader swallows callback
            # exceptions, so a violation must land in errors[]
            if int(i) != len(tokens):
                errors.append(f"out-of-order INDEX {i} at {len(tokens)}")
                done.set()
                return
            tokens.append(int(t))
        if len(toks) > 1:
            print(f"  (coalesced message: {len(toks)} tokens)")
    params = response.parameters
    if ("triton_final_response" in params
            and params["triton_final_response"].bool_param):
        done.set()


client = InferenceServerClient(args.url, verbose=args.verbose)
client.start_stream(callback)
prompt = np.array([5, 6, 7], dtype=np.int32)
inp = InferInput("INPUT_IDS", [len(prompt)], "INT32")
inp.set_data_from_numpy(prompt)
client.async_stream_infer(
    "moe_gpt_mc", [inp], request_id="gen-1",
    parameters={"max_tokens": args.max_tokens, "response_coalesce": True})
if not done.wait(300):
    sys.exit("error: stream did not finish")
client.stop_stream()
client.close()
if errors:
    sys.exit(f"error: {errors[0]}")
if len(tokens) != args.max_tokens:
    sys.exit(f"error: expected {args.max_tokens} tokens, got {len(tokens)}")
print(f"streamed {len(tokens)} tokens: {tokens}")
print("PASS: moe_gpt_stream")
