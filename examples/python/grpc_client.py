#!/usr/bin/env python3
"""Minimal raw-stub gRPC client: no client-library convenience layer, just
the generated protobuf messages and the service stub — server metadata,
model metadata, then an add/sub inference with raw_input_contents and
hand-decoded raw_output_contents.

Reference counterpart: src/python/examples/grpc_client.py (generated-stub
usage against the `simple` model).
"""

import argparse
import sys

import grpc
import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

channel = grpc.insecure_channel(args.url)
stub = GRPCInferenceServiceStub(channel)

meta = stub.ServerMetadata(pb.ServerMetadataRequest())
print(f"server: {meta.name} {meta.version}")

model_meta = stub.ModelMetadata(pb.ModelMetadataRequest(name="simple"))
print(f"model: {model_meta.name} "
      f"inputs={[t.name for t in model_meta.inputs]}")

request = pb.ModelInferRequest(model_name="simple", id="raw-stub")
in0 = np.arange(16, dtype=np.int32)
in1 = np.full(16, 2, dtype=np.int32)
for name in ("INPUT0", "INPUT1"):
    request.inputs.add(name=name, datatype="INT32", shape=[1, 16])
request.raw_input_contents.append(in0.tobytes())
request.raw_input_contents.append(in1.tobytes())
request.outputs.add(name="OUTPUT0")
request.outputs.add(name="OUTPUT1")

response = stub.ModelInfer(request)

outputs = {}
for tensor, raw in zip(response.outputs, response.raw_output_contents):
    outputs[tensor.name] = np.frombuffer(raw, np.int32)
if not np.array_equal(outputs["OUTPUT0"], in0 + in1):
    sys.exit(f"error: bad sum {outputs['OUTPUT0']}")
if not np.array_equal(outputs["OUTPUT1"], in0 - in1):
    sys.exit(f"error: bad difference {outputs['OUTPUT1']}")

print("PASS: raw-stub grpc client")
