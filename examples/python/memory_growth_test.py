#!/usr/bin/env python3
"""Memory-growth probe: many inferences while polling process RSS; fails if
resident memory keeps climbing.

Reference counterpart: src/python/examples/memory_growth_test.py:98 (RSS
polling around repeated inferences, paired with the C++ memory_leak_test).
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput


def rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
parser.add_argument("-n", "--iterations", type=int, default=500)
parser.add_argument("--max-growth-kb", type=int, default=50_000)
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    input0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1 = np.ones((1, 16), dtype=np.int32)
    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(input0)
    inputs[1].set_data_from_numpy(input1)

    # warmup, then baseline after allocator steady-state
    for _ in range(50):
        client.infer("simple", inputs)
    base = rss_kb()
    for i in range(args.iterations):
        client.infer("simple", inputs)
        if i % 100 == 0:
            print(f"iter {i}: RSS {rss_kb()} kB")
    growth = rss_kb() - base
    print(f"RSS growth over {args.iterations} inferences: {growth} kB")
    if growth > args.max_growth_kb:
        sys.exit(f"error: RSS grew {growth} kB > {args.max_growth_kb} kB")

print("PASS: memory growth bounded")
