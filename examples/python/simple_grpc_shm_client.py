#!/usr/bin/env python3
"""System shared-memory data plane over gRPC.

Reference counterpart: src/python/examples/simple_grpc_shm_client.py.
"""

import argparse
import sys

import numpy as np

import client_tpu.utils.shared_memory as shm
from client_tpu.grpc import InferenceServerClient, InferInput, \
    InferRequestedOutput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    client.unregister_system_shared_memory()

    input0_data = np.arange(16, dtype=np.int32)
    input1_data = np.ones(16, dtype=np.int32)
    byte_size = input0_data.nbytes

    shm_ip = shm.create_shared_memory_region("input_data", "/py_grpc_shm_in",
                                             byte_size * 2)
    shm.set_shared_memory_region(shm_ip, [input0_data])
    shm.set_shared_memory_region(shm_ip, [input1_data], offset=byte_size)
    shm_op = shm.create_shared_memory_region("output_data", "/py_grpc_shm_out",
                                             byte_size * 2)
    client.register_system_shared_memory("input_data", "/py_grpc_shm_in",
                                         byte_size * 2)
    client.register_system_shared_memory("output_data", "/py_grpc_shm_out",
                                         byte_size * 2)

    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_shared_memory("input_data", byte_size)
    inputs[1].set_shared_memory("input_data", byte_size, offset=byte_size)
    outputs = [InferRequestedOutput("OUTPUT0"),
               InferRequestedOutput("OUTPUT1")]
    outputs[0].set_shared_memory("output_data", byte_size)
    outputs[1].set_shared_memory("output_data", byte_size, offset=byte_size)

    client.infer("simple", inputs, outputs=outputs)

    output0 = shm.get_contents_as_numpy(shm_op, np.int32, [1, 16])
    output1 = shm.get_contents_as_numpy(shm_op, np.int32, [1, 16],
                                        offset=byte_size)
    if not np.array_equal(output0[0], input0_data + input1_data):
        sys.exit("error: incorrect sum")
    if not np.array_equal(output1[0], input0_data - input1_data):
        sys.exit("error: incorrect difference")

    client.unregister_system_shared_memory()
    shm.destroy_shared_memory_region(shm_ip)
    shm.destroy_shared_memory_region(shm_op)

print("PASS: system shared memory (grpc)")
