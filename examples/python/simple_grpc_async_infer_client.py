#!/usr/bin/env python3
"""Async gRPC client: callback-style async_infer over grpc futures.

Reference counterpart: src/python/examples/simple_grpc_async_infer_client.py.
"""

import argparse
import queue
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-n", "--requests", type=int, default=8)
args = parser.parse_args()

results: "queue.Queue" = queue.Queue()

with InferenceServerClient(args.url) as client:
    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 2, dtype=np.int32)
    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    def callback(result, error):
        results.put((result, error))

    for i in range(args.requests):
        client.async_infer("simple", inputs, callback, request_id=str(i))

    for _ in range(args.requests):
        result, error = results.get(timeout=120)
        if error is not None:
            sys.exit(f"error: {error}")
        if not np.array_equal(result.as_numpy("OUTPUT0"),
                              input0_data + input1_data):
            sys.exit("error: incorrect sum")

print(f"PASS: {args.requests} async requests")
