#!/usr/bin/env python3
"""gRPC client with explicit keepalive options: HTTP/2 PING-based liveness
on the channel, then a value-asserted inference.

Reference counterpart: src/python/examples/simple_grpc_keepalive_client.py
(KeepAliveOptions mirroring reference grpc/__init__.py:104-144).
"""

import argparse
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput, KeepAliveOptions

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

keepalive = KeepAliveOptions(
    keepalive_time_ms=2**31 - 1,
    keepalive_timeout_ms=20000,
    keepalive_permit_without_calls=False,
    http2_max_pings_without_data=2,
)

with InferenceServerClient(args.url, keepalive_options=keepalive) as client:
    in0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    in1 = np.ones((1, 16), dtype=np.int32)
    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)

    result = client.infer("simple", inputs)

    if not np.array_equal(result.as_numpy("OUTPUT0"), in0 + in1):
        sys.exit("error: incorrect sum")
    if not np.array_equal(result.as_numpy("OUTPUT1"), in0 - in1):
        sys.exit("error: incorrect difference")

print("PASS: keepalive (grpc)")
