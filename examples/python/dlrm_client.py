#!/usr/bin/env python3
"""DLRM recommendation client: ragged CSR embedding lookups over HTTP or
gRPC.

Each request carries a dense feature row per example plus, for every
(example, sparse-feature) bag, a variable-length run of embedding-row
ids in CSR form — ``INDICES`` holds all ids concatenated, ``OFFSETS``
the bag boundaries (``OFFSETS[0] == 0``, last element == total lookups).
The server micro-batches by summed lookup count, not rows.

The script asserts the scores are deterministic (two identical requests
return byte-identical results — static bucket shapes and fixed-seed
weights guarantee it) and prints them, so a harness can diff the HTTP
and gRPC transports against each other.
"""

import argparse
import sys

import numpy as np

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default=None)
parser.add_argument("-i", "--protocol", default="http",
                    choices=["http", "grpc"])
parser.add_argument("-m", "--model", default="dlrm")
parser.add_argument("-b", "--batch-size", type=int, default=2)
parser.add_argument("--tables", type=int, default=4,
                    help="sparse features per example (model num_tables)")
parser.add_argument("--rows", type=int, default=64,
                    help="embedding rows per table (id range)")
parser.add_argument("--seed", type=int, default=20)
args = parser.parse_args()

if args.protocol == "grpc":
    from client_tpu.grpc import InferenceServerClient, InferInput
    url = args.url or "localhost:8001"
else:
    from client_tpu.http import InferenceServerClient, InferInput
    url = args.url or "localhost:8000"

rng = np.random.default_rng(args.seed)
bags = args.batch_size * args.tables
counts = rng.integers(0, 5, size=bags)
indices = rng.integers(0, args.rows, size=int(counts.sum())).astype(np.int32)
offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
dense = rng.standard_normal((args.batch_size, 8)).astype(np.float32)

with InferenceServerClient(url) as client:
    inputs = [InferInput("DENSE", list(dense.shape), "FP32"),
              InferInput("INDICES", [int(indices.shape[0])], "INT32"),
              InferInput("OFFSETS", [int(offsets.shape[0])], "INT32")]
    inputs[0].set_data_from_numpy(dense)
    inputs[1].set_data_from_numpy(indices)
    inputs[2].set_data_from_numpy(offsets)

    first = client.infer(args.model, inputs).as_numpy("OUTPUT0")
    again = client.infer(args.model, inputs).as_numpy("OUTPUT0")

if first.shape != (args.batch_size, 1):
    sys.exit(f"error: OUTPUT0 shape {first.shape}, "
             f"expected {(args.batch_size, 1)}")
if not np.all(np.isfinite(first)):
    sys.exit("error: non-finite scores")
if not np.array_equal(first, again):
    sys.exit("error: identical requests returned different scores")

for b in range(args.batch_size):
    print(f"scores[{b}]: {first[b, 0]:.6f} "
          f"({int(offsets[(b + 1) * args.tables] - offsets[b * args.tables])}"
          " lookups)")
print(f"PASS: dlrm ({args.protocol})")
