#!/usr/bin/env python3
"""TPU shared-memory data plane over HTTP: device-resident I/O regions
registered by base64-serialized buffer handle — the TPU-native replacement
for the reference's CUDA-IPC flow over REST.

Reference counterpart: src/python/examples/simple_http_cudashm_client.py
(cudaMalloc -> cudaIpcGetMemHandle -> base64 handle -> register -> infer ->
cudaMemcpy back; here the handle comes from tpu_shared_memory.get_raw_handle).
"""

import argparse
import sys

import numpy as np

import client_tpu.utils.tpu_shared_memory as tpushm
from client_tpu.http import InferenceServerClient, InferInput, \
    InferRequestedOutput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    client.unregister_tpu_shared_memory()

    input0_data = np.arange(16, dtype=np.int32)
    input1_data = np.ones(16, dtype=np.int32)
    byte_size = input0_data.nbytes

    shm_ip0 = tpushm.create_shared_memory_region("input0_data", byte_size, 0)
    shm_ip1 = tpushm.create_shared_memory_region("input1_data", byte_size, 0)
    shm_op = tpushm.create_shared_memory_region("output_data", byte_size * 2,
                                                0)
    tpushm.set_shared_memory_region(shm_ip0, [input0_data])
    tpushm.set_shared_memory_region(shm_ip1, [input1_data])

    client.register_tpu_shared_memory(
        "input0_data", tpushm.get_raw_handle(shm_ip0), 0, byte_size)
    client.register_tpu_shared_memory(
        "input1_data", tpushm.get_raw_handle(shm_ip1), 0, byte_size)
    client.register_tpu_shared_memory(
        "output_data", tpushm.get_raw_handle(shm_op), 0, byte_size * 2)

    status = client.get_tpu_shared_memory_status()
    if len(status.get("regions", status)) < 3:
        sys.exit("error: regions missing from status")

    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_shared_memory("input0_data", byte_size)
    inputs[1].set_shared_memory("input1_data", byte_size)
    outputs = [InferRequestedOutput("OUTPUT0"),
               InferRequestedOutput("OUTPUT1")]
    outputs[0].set_shared_memory("output_data", byte_size)
    outputs[1].set_shared_memory("output_data", byte_size, offset=byte_size)

    client.infer("simple", inputs, outputs=outputs)

    output0 = tpushm.get_contents_as_numpy(shm_op, np.int32, [1, 16])
    output1 = tpushm.get_contents_as_numpy(shm_op, np.int32, [1, 16],
                                           offset=byte_size)
    if not np.array_equal(output0[0], input0_data + input1_data):
        sys.exit("error: incorrect sum")
    if not np.array_equal(output1[0], input0_data - input1_data):
        sys.exit("error: incorrect difference")

    client.unregister_tpu_shared_memory()
    for h in (shm_ip0, shm_ip1, shm_op):
        tpushm.destroy_shared_memory_region(h)

print("PASS: tpu shared memory (http)")
