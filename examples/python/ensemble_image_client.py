#!/usr/bin/env python3
"""Ensemble pipeline client: raw HxWx3 bytes -> image_preprocess ->
resnet50, one request end to end.

Reference counterpart: src/c++/examples/ensemble_image_client.cc:365 /
the preprocess+classify ensemble flow.
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
args = parser.parse_args()

rng = np.random.default_rng(11)
raw = rng.integers(0, 256, size=(480, 640, 3), dtype=np.uint8)

with InferenceServerClient(args.url) as client:
    inp = InferInput("RAW_IMAGE", [1, *raw.shape], "UINT8")
    inp.set_data_from_numpy(raw[None])
    result = client.infer("ensemble_image", [inp])
    logits = result.as_numpy("CLASS_LOGITS")
    if logits.shape[-1] != 1000 or not np.isfinite(logits).all():
        sys.exit(f"error: bad logits {logits.shape}")
    print("top class:", int(np.argmax(logits)))

print("PASS: ensemble image")
