#!/usr/bin/env python3
"""Launches the framework's HTTP + gRPC inference servers for the examples.

The reference examples assume an externally-started tritonserver with the
`simple*` models (README.md usage sections); this framework ships its own
engine, so one command brings up everything the examples in this directory
talk to:

    python examples/python/serve.py [--models simple,simple_string,...]
                                    [--http-port 8000] [--grpc-port 8001]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from client_tpu.engine import TpuEngine  # noqa: E402
from client_tpu.models import build_repository  # noqa: E402
from client_tpu.server import HttpInferenceServer  # noqa: E402
from client_tpu.server.grpc_server import GrpcInferenceServer  # noqa: E402

DEFAULT_MODELS = ("simple,simple_string,simple_identity,simple_sequence,"
                  "simple_repeat")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default=DEFAULT_MODELS,
                    help="comma-separated model-zoo names (see "
                         "client_tpu/models); pass 'all' for every model")
    ap.add_argument("--http-port", type=int, default=8000)
    ap.add_argument("--grpc-port", type=int, default=8001)
    args = ap.parse_args()

    names = None if args.models == "all" else [
        n.strip() for n in args.models.split(",") if n.strip()]
    engine = TpuEngine(build_repository(names))
    http_srv = HttpInferenceServer(engine, port=args.http_port).start()
    grpc_srv = GrpcInferenceServer(engine, port=args.grpc_port).start()
    print(f"HTTP  : {http_srv.url}")
    print(f"gRPC  : 127.0.0.1:{grpc_srv.port}")
    print("Ctrl-C to stop.")
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        pass
    finally:
        grpc_srv.stop()
        http_srv.stop()
        engine.shutdown()


if __name__ == "__main__":
    main()
