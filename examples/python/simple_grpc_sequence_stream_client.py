#!/usr/bin/env python3
"""Stateful sequences over one bidi stream: two interleaved accumulator
sequences with start/end flags, validated per-sequence running totals.

Reference counterpart:
src/python/examples/simple_grpc_sequence_stream_infer_client.py.
"""

import argparse
import queue
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

responses: "queue.Queue" = queue.Queue()


def callback(result, error):
    responses.put((result, error))


with InferenceServerClient(args.url) as client:
    client.start_stream(callback)

    seq_a, seq_b = 1001, 1002
    a_vals, b_vals = [1, 2, 3], [10, 20, 30]
    expected = {}
    a_total = b_total = 0
    for i in range(3):
        for seq, vals in ((seq_a, a_vals), (seq_b, b_vals)):
            value = vals[i]
            if seq == seq_a:
                a_total += value
                expected[f"A{i}"] = a_total
                rid = f"A{i}"
            else:
                b_total += value
                expected[f"B{i}"] = b_total
                rid = f"B{i}"
            inp = InferInput("INPUT", [1], "INT32")
            inp.set_data_from_numpy(np.array([value], dtype=np.int32))
            client.async_stream_infer("simple_sequence", [inp],
                                      request_id=rid, sequence_id=seq,
                                      sequence_start=i == 0,
                                      sequence_end=i == 2)

    got = {}
    for _ in range(len(expected)):
        result, error = responses.get(timeout=120)
        if error is not None:
            sys.exit(f"error: {error}")
        rid = result.get_response().id
        got[rid] = int(result.as_numpy("OUTPUT")[0])
    client.stop_stream()

    if got != expected:
        sys.exit(f"error: {got} != {expected}")

print("PASS: sequence streaming")
