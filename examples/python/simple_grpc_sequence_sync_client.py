#!/usr/bin/env python3
"""Synchronous stateful sequences over gRPC: two interleaved accumulator
sequences with correlation ids and start/end flags.

Reference counterpart:
src/python/examples/simple_grpc_sequence_sync_infer_client.py.
"""

import argparse
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()


def step(client, seq_id, start, end, value):
    inp = InferInput("INPUT", [1], "INT32")
    inp.set_data_from_numpy(np.array([value], dtype=np.int32))
    result = client.infer("simple_sequence", [inp], sequence_id=seq_id,
                          sequence_start=start, sequence_end=end)
    return int(result.as_numpy("OUTPUT")[0])


with InferenceServerClient(args.url) as client:
    seq_a, seq_b = 201, 202
    a_total = b_total = 0
    values = [(1, 100), (2, 200), (3, 300)]
    for i, (a, b) in enumerate(values):
        a_total += a
        b_total += b
        got_a = step(client, seq_a, i == 0, i == len(values) - 1, a)
        got_b = step(client, seq_b, i == 0, i == len(values) - 1, b)
        if got_a != a_total or got_b != b_total:
            sys.exit(f"error: state mismatch at step {i}: "
                     f"{got_a}/{a_total}, {got_b}/{b_total}")

print("PASS: sequence sync (grpc)")
