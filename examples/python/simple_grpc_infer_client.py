#!/usr/bin/env python3
"""Value-asserting add/sub client over gRPC.

Reference counterpart: src/python/examples/simple_grpc_infer_client.py.
"""

import argparse
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput, \
    InferRequestedOutput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-v", "--verbose", action="store_true")
args = parser.parse_args()

with InferenceServerClient(args.url, verbose=args.verbose) as client:
    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.ones((1, 16), dtype=np.int32)
    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(input0_data)
    # use_contents exercises the typed-contents (non-raw) proto path
    inputs[1].set_data_from_numpy(input1_data, use_contents=True)

    outputs = [InferRequestedOutput("OUTPUT0"),
               InferRequestedOutput("OUTPUT1")]
    result = client.infer("simple", inputs, outputs=outputs, request_id="1")

    output0 = result.as_numpy("OUTPUT0")
    output1 = result.as_numpy("OUTPUT1")
    if not np.array_equal(output0, input0_data + input1_data):
        sys.exit("error: incorrect sum")
    if not np.array_equal(output1, input0_data - input1_data):
        sys.exit("error: incorrect difference")
    if args.verbose:
        print("OUTPUT0:", output0)
        print("OUTPUT1:", output1)

print("PASS: infer")
