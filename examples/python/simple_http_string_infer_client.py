#!/usr/bin/env python3
"""BYTES (string tensor) client: decimal strings through the 4-byte-LE
length-prefixed codec, validated add/sub results.

Reference counterpart: src/python/examples/simple_http_string_infer_client.py.
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    in0 = np.array([[str(i) for i in range(16)]], dtype=object)
    in1 = np.array([["1"] * 16], dtype=object)
    inputs = [InferInput("INPUT0", [1, 16], "BYTES"),
              InferInput("INPUT1", [1, 16], "BYTES")]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1, binary_data=False)

    result = client.infer("simple_string", inputs)
    out0 = result.as_numpy("OUTPUT0")
    out1 = result.as_numpy("OUTPUT1")
    for i in range(16):
        if int(out0[0][i]) != i + 1 or int(out1[0][i]) != i - 1:
            sys.exit(f"error: bad result at {i}: {out0[0][i]} {out1[0][i]}")

print("PASS: string infer")
