#!/usr/bin/env python3
"""Decoupled-model client: one request to `simple_repeat` yields N streamed
responses on the bidi stream (the reference's custom repeat model flow,
src/python/examples/simple_grpc_custom_repeat.py — decoupled transaction
policy, one-to-many responses).
"""

import argparse
import queue
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-n", "--repeat", type=int, default=4)
args = parser.parse_args()

responses: "queue.Queue" = queue.Queue()


def callback(result, error):
    responses.put((result, error))


with InferenceServerClient(args.url) as client:
    client.start_stream(callback)
    values = np.arange(args.repeat, dtype=np.int32)
    inp = InferInput("IN", [args.repeat], "INT32")
    inp.set_data_from_numpy(values)
    client.async_stream_infer("simple_repeat", [inp], request_id="r1")

    got = []
    for _ in range(args.repeat):
        result, error = responses.get(timeout=120)
        if error is not None:
            sys.exit(f"error: {error}")
        got.append(int(result.as_numpy("OUT")[0]))
    client.stop_stream()

    if got != list(values):
        sys.exit(f"error: {got} != {list(values)}")

print(f"PASS: decoupled repeat ({args.repeat} responses from one request)")
