#!/usr/bin/env python3
"""Token-streaming generation client: drives the `tiny_gpt` generative
model over the gRPC bidi stream, printing tokens as they arrive.

No reference counterpart (the reference's only decoupled example is the
repeat demo, src/python/examples/simple_grpc_custom_repeat.py) — this is
the framework's generative-serving demo: the server batches every decode
step across all concurrent streams (continuous batching over a KV-cache
arena), and this client shows that the stream protocol is the ordinary
decoupled one.
"""

import argparse
import sys
import threading

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-p", "--prompt", default="7,8,9",
                    help="comma-separated token ids")
parser.add_argument("-n", "--max-tokens", type=int, default=8)
args = parser.parse_args()

prompt = np.array([int(x) for x in args.prompt.split(",")], dtype=np.int32)

tokens: list[int] = []
done = threading.Event()
errors: list[str] = []


def callback(result, error):
    if error is not None:
        errors.append(str(error))
        done.set()
        return
    response = result.get_response()
    params = response.parameters
    if response.outputs:
        idx = int(result.as_numpy("INDEX")[0])
        tok = int(result.as_numpy("TOKEN")[0])
        if idx != len(tokens):
            errors.append(f"out-of-order token index {idx}")
        tokens.append(tok)
        print(f"token[{idx}] = {tok}", flush=True)
    if ("triton_final_response" in params
            and params["triton_final_response"].bool_param):
        done.set()


with InferenceServerClient(args.url) as client:
    client.start_stream(callback)
    inp = InferInput("INPUT_IDS", [len(prompt)], "INT32")
    inp.set_data_from_numpy(prompt)
    client.async_stream_infer("tiny_gpt", [inp], request_id="gen-0",
                              parameters={"max_tokens": args.max_tokens})
    if not done.wait(timeout=300):
        sys.exit("error: stream did not finish")
    client.stop_stream()

if errors:
    sys.exit(f"error: {errors[0]}")
if len(tokens) != args.max_tokens:
    sys.exit(f"error: expected {args.max_tokens} tokens, got {len(tokens)}")

print(f"PASS: streamed {len(tokens)} generated tokens")
