#!/usr/bin/env python3
"""Control plane over HTTP: liveness, readiness, metadata, config,
repository index, statistics.

Reference counterpart: src/python/examples/simple_http_health_metadata.py.
"""

import argparse
import sys

from client_tpu.http import InferenceServerClient

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
args = parser.parse_args()

with InferenceServerClient(args.url) as client:
    if not client.is_server_live():
        sys.exit("error: server not live")
    if not client.is_server_ready():
        sys.exit("error: server not ready")
    if not client.is_model_ready("simple"):
        sys.exit("error: model not ready")

    meta = client.get_server_metadata()
    print(f"server: {meta['name']} {meta['version']}")
    model_meta = client.get_model_metadata("simple")
    assert model_meta["name"] == "simple", model_meta
    config = client.get_model_config("simple")
    assert config["name"] == "simple", config
    index = client.get_model_repository_index()
    assert any(m["name"] == "simple" for m in index), index
    stats = client.get_inference_statistics("simple")
    assert "model_stats" in stats, stats

print("PASS: health and metadata")
