#!/usr/bin/env python3
"""Raw-stub gRPC image classification client: builds ModelInferRequest
protos by hand (no client-library layer), preprocesses NHWC FP32 images,
and decodes the classification extension's "score:index" BYTES entries.

Reference counterpart: src/python/examples/grpc_image_client.py (generated
stubs, model-metadata-driven preprocessing, classification parameter).
Accepts image files when PIL is available; --synthetic generates a
deterministic test image.
"""

import argparse
import struct
import sys

import grpc
import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub

parser = argparse.ArgumentParser()
parser.add_argument("image", nargs="*", help="image file(s) (needs PIL)")
parser.add_argument("-m", "--model", default="resnet50")
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-b", "--batch-size", type=int, default=1)
parser.add_argument("-c", "--classes", type=int, default=3)
parser.add_argument("--synthetic", action="store_true",
                    help="use a generated test image instead of files")
args = parser.parse_args()


def load_images():
    if args.image and not args.synthetic:
        try:
            from PIL import Image
        except ImportError:
            sys.exit("PIL not available; rerun with --synthetic")
        arrays = []
        for path in args.image:
            img = Image.open(path).convert("RGB").resize((224, 224))
            arrays.append(np.asarray(img, dtype=np.float32) / 255.0)
        return arrays
    rng = np.random.default_rng(7)
    return [rng.random((224, 224, 3), dtype=np.float32)
            for _ in range(args.batch_size)]


channel = grpc.insecure_channel(args.url)
stub = GRPCInferenceServiceStub(channel)

# Model metadata drives the input wiring, as in the reference client.
meta = stub.ModelMetadata(pb.ModelMetadataRequest(name=args.model))
input_name = meta.inputs[0].name
output_name = meta.outputs[0].name

batch = np.stack(load_images()[:args.batch_size]).astype(np.float32)

request = pb.ModelInferRequest(model_name=args.model)
request.inputs.add(name=input_name, datatype="FP32",
                   shape=list(batch.shape))
request.raw_input_contents.append(batch.tobytes())
out = request.outputs.add(name=output_name)
out.parameters["classification"].int64_param = args.classes

response = stub.ModelInfer(request)

# Classification entries come back as a BYTES tensor: 4-byte LE length
# prefix per "score:index[:label]" element.
raw = response.raw_output_contents[0]
entries, pos = [], 0
while pos + 4 <= len(raw):
    (n,) = struct.unpack_from("<I", raw, pos)
    pos += 4
    entries.append(raw[pos:pos + n].decode())
    pos += n
if not entries:
    sys.exit("error: no classification entries returned")
per_image = max(1, len(entries) // batch.shape[0])
for n in range(batch.shape[0]):
    print(f"image {n}:")
    for text in entries[n * per_image:(n + 1) * per_image]:
        print(f"    {text}")
        float(text.split(":")[0])  # entries must be "score:index[:label]"

print("PASS: raw-stub image client")
