#!/usr/bin/env python3
"""Object-lifecycle client: reuses InferInput/InferRequestedOutput objects
across many requests and both protocols, asserting results stay correct.

Reference counterpart: src/c++/examples/reuse_infer_objects_client.cc:482
(the reference validates tensor-object reuse across sync/async/shm flows).
"""

import argparse
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient as GrpcClient
from client_tpu.grpc import InferInput as GrpcInput
from client_tpu.http import InferenceServerClient as HttpClient
from client_tpu.http import InferInput as HttpInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--http-url", default="localhost:8000")
parser.add_argument("-g", "--grpc-url", default="localhost:8001")
parser.add_argument("-n", "--iterations", type=int, default=10)
args = parser.parse_args()

for label, Client, Input, url in (
        ("http", HttpClient, HttpInput, args.http_url),
        ("grpc", GrpcClient, GrpcInput, args.grpc_url)):
    with Client(url) as client:
        inputs = [Input("INPUT0", [1, 16], "INT32"),
                  Input("INPUT1", [1, 16], "INT32")]
        for i in range(args.iterations):
            # new data through the SAME input objects each iteration
            a = np.full((1, 16), i, dtype=np.int32)
            b = np.full((1, 16), 2 * i + 1, dtype=np.int32)
            inputs[0].set_data_from_numpy(a)
            inputs[1].set_data_from_numpy(b)
            result = client.infer("simple", inputs)
            if not np.array_equal(result.as_numpy("OUTPUT0"), a + b):
                sys.exit(f"error: {label} iteration {i} wrong sum")
            if not np.array_equal(result.as_numpy("OUTPUT1"), a - b):
                sys.exit(f"error: {label} iteration {i} wrong difference")
    print(f"{label}: {args.iterations} iterations with reused objects OK")

print("PASS: object reuse")
