#!/usr/bin/env python3
"""Value-asserting add/sub client over HTTP.

Reference counterpart: src/python/examples/simple_http_infer_client.py —
sends two INT32[1,16] tensors to `simple` and validates OUTPUT0=a+b,
OUTPUT1=a-b elementwise.
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput, \
    InferRequestedOutput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
parser.add_argument("-v", "--verbose", action="store_true")
args = parser.parse_args()

with InferenceServerClient(args.url, verbose=args.verbose) as client:
    inputs = []
    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.ones((1, 16), dtype=np.int32)
    inputs.append(InferInput("INPUT0", [1, 16], "INT32"))
    inputs.append(InferInput("INPUT1", [1, 16], "INT32"))
    inputs[0].set_data_from_numpy(input0_data, binary_data=True)
    inputs[1].set_data_from_numpy(input1_data, binary_data=False)

    outputs = [InferRequestedOutput("OUTPUT0", binary_data=True),
               InferRequestedOutput("OUTPUT1", binary_data=False)]

    result = client.infer("simple", inputs, outputs=outputs, request_id="1")

    output0 = result.as_numpy("OUTPUT0")
    output1 = result.as_numpy("OUTPUT1")
    for i in range(16):
        if args.verbose:
            print(f"{input0_data[0][i]} + {input1_data[0][i]} = "
                  f"{output0[0][i]}")
        if output0[0][i] != input0_data[0][i] + input1_data[0][i]:
            sys.exit("error: incorrect sum")
        if output1[0][i] != input0_data[0][i] - input1_data[0][i]:
            sys.exit("error: incorrect difference")

print("PASS: infer")
