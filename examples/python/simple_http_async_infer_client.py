#!/usr/bin/env python3
"""Async HTTP client: N concurrent requests through the client's thread
pool, results gathered from futures.

Reference counterpart: src/python/examples/simple_http_async_infer_client.py
(greenlet pool there; a thread pool here).
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
parser.add_argument("-n", "--requests", type=int, default=8)
args = parser.parse_args()

with InferenceServerClient(args.url, concurrency=4) as client:
    input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1_data = np.full((1, 16), 3, dtype=np.int32)
    inputs = [InferInput("INPUT0", [1, 16], "INT32"),
              InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(input0_data)
    inputs[1].set_data_from_numpy(input1_data)

    async_requests = [
        client.async_infer("simple", inputs, request_id=str(i))
        for i in range(args.requests)
    ]
    for req in async_requests:
        result = req.get_result(timeout=120)
        if not np.array_equal(result.as_numpy("OUTPUT0"),
                              input0_data + input1_data):
            sys.exit("error: incorrect sum")

print(f"PASS: {args.requests} async requests")
