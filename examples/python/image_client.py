#!/usr/bin/env python3
"""Image classification client (ResNet-50): preprocess, infer (HTTP or
gRPC, sync or async, batched), print top-K classes via the classification
extension.

Reference counterpart: src/python/examples/image_client.py (PIL preprocess,
-m/-b/-c/-s flags, async/streaming variants). Accepts image files when PIL
is available; otherwise --synthetic generates a deterministic test image.
"""

import argparse
import sys

import numpy as np

parser = argparse.ArgumentParser()
parser.add_argument("image", nargs="*", help="image file(s) (needs PIL)")
parser.add_argument("-m", "--model", default="resnet50")
parser.add_argument("-u", "--url", default=None)
parser.add_argument("-i", "--protocol", default="http",
                    choices=["http", "grpc"])
parser.add_argument("-b", "--batch-size", type=int, default=1)
parser.add_argument("-c", "--classes", type=int, default=3,
                    help="top-K classes (classification extension)")
parser.add_argument("-a", "--async", dest="use_async", action="store_true")
parser.add_argument("--synthetic", action="store_true",
                    help="use a generated test image instead of files")
args = parser.parse_args()


def load_images():
    if args.image and not args.synthetic:
        try:
            from PIL import Image
        except ImportError:
            sys.exit("PIL not available; rerun with --synthetic")
        arrays = []
        for path in args.image:
            img = Image.open(path).convert("RGB").resize((224, 224))
            arrays.append(np.asarray(img, dtype=np.float32) / 255.0)
        return arrays
    rng = np.random.default_rng(7)
    return [rng.random((224, 224, 3), dtype=np.float32)
            for _ in range(args.batch_size)]


if args.protocol == "grpc":
    from client_tpu.grpc import InferenceServerClient, InferInput, \
        InferRequestedOutput
    url = args.url or "localhost:8001"
else:
    from client_tpu.http import InferenceServerClient, InferInput, \
        InferRequestedOutput
    url = args.url or "localhost:8000"

images = load_images()
batch = np.stack(images[:args.batch_size]).astype(np.float32)

with InferenceServerClient(url) as client:
    inp = InferInput("INPUT", list(batch.shape), "FP32")
    inp.set_data_from_numpy(batch)
    out = InferRequestedOutput("OUTPUT", class_count=args.classes)

    if args.use_async and args.protocol == "http":
        result = client.async_infer(args.model, [inp],
                                    outputs=[out]).get_result(timeout=300)
    else:
        result = client.infer(args.model, [inp], outputs=[out])

    # classification extension: BYTES "score:index[:label]" per class
    classes = result.as_numpy("OUTPUT")
    for n, row in enumerate(classes):
        print(f"image {n}:")
        for entry in np.ravel(row)[:args.classes]:
            text = entry.decode() if isinstance(entry, bytes) else str(entry)
            print(f"    {text}")

print("PASS: image classification")
