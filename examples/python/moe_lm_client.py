#!/usr/bin/env python3
"""Next-token query against the served Switch-MoE LM (`moe_lm_mc`).

No reference counterpart (the reference serves no models, SURVEY.md §2.8);
this demonstrates the expert-parallel model family: experts are sharded
over the server mesh's ``ep`` axis, invisible to the client — the wire
contract is plain KServe v2.

Serve with: python -m client_tpu.server --zoo moe_lm_mc
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8000")
parser.add_argument("-v", "--verbose", action="store_true")
args = parser.parse_args()

with InferenceServerClient(args.url, verbose=args.verbose) as client:
    # The model declares a fixed sequence length — read it from metadata
    # rather than guessing (control-plane round trip, KServe v2).
    md = client.get_model_metadata("moe_lm_mc")
    seq_len = int(md["inputs"][0]["shape"][-1])
    ids = (np.arange(seq_len, dtype=np.int32) % 256).reshape(1, -1)
    inp = InferInput("INPUT_IDS", list(ids.shape), "INT32")
    inp.set_data_from_numpy(ids)
    result = client.infer("moe_lm_mc", [inp])
    logits = result.as_numpy("LOGITS")
    if logits.shape[:2] != (1, seq_len) or not np.isfinite(
            logits).all():
        sys.exit(f"error: bad logits {logits.shape}")
    next_tok = int(np.argmax(logits[0, -1]))
    print(f"next-token argmax: {next_tok} "
          f"(logits {logits.shape}, vocab {logits.shape[-1]})")
    print("PASS: moe_lm")
