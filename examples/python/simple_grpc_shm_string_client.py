#!/usr/bin/env python3
"""BYTES (string) tensors through system shared memory over gRPC: inputs
serialized with the 4-byte-length-prefixed codec into shm regions, outputs
read back out of a shm region and deserialized.

Reference counterpart: src/python/examples/simple_grpc_shm_string_client.py.
"""

import argparse
import sys

import numpy as np

import client_tpu.utils.shared_memory as shm
from client_tpu.grpc import InferenceServerClient, InferInput, \
    InferRequestedOutput
from client_tpu.utils import serialize_byte_tensor, serialized_byte_size

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

in0 = np.arange(16, dtype=np.int32)
in1 = np.ones(16, dtype=np.int32)
in0_str = np.array([str(x).encode() for x in in0], dtype=np.object_)
in1_str = np.array([str(x).encode() for x in in1], dtype=np.object_)

in0_ser = serialize_byte_tensor(in0_str)
in1_ser = serialize_byte_tensor(in1_str)
in0_size = serialized_byte_size(in0_ser)
in1_size = serialized_byte_size(in1_ser)
# Sums/differences serialize no longer than inputs + sign slack per element.
out_size = max(in0_size, in1_size) + 16

with InferenceServerClient(args.url) as client:
    client.unregister_system_shared_memory()

    shm_ip = shm.create_shared_memory_region(
        "input_data", "/py_grpc_shm_str_in", in0_size + in1_size)
    shm.set_shared_memory_region(shm_ip, [in0_str])
    shm.set_shared_memory_region(shm_ip, [in1_str], offset=in0_size)
    shm_op0 = shm.create_shared_memory_region(
        "output0_data", "/py_grpc_shm_str_out0", out_size)
    shm_op1 = shm.create_shared_memory_region(
        "output1_data", "/py_grpc_shm_str_out1", out_size)

    client.register_system_shared_memory(
        "input_data", "/py_grpc_shm_str_in", in0_size + in1_size)
    client.register_system_shared_memory(
        "output0_data", "/py_grpc_shm_str_out0", out_size)
    client.register_system_shared_memory(
        "output1_data", "/py_grpc_shm_str_out1", out_size)

    inputs = [InferInput("INPUT0", [1, 16], "BYTES"),
              InferInput("INPUT1", [1, 16], "BYTES")]
    inputs[0].set_shared_memory("input_data", in0_size)
    inputs[1].set_shared_memory("input_data", in1_size, offset=in0_size)
    outputs = [InferRequestedOutput("OUTPUT0"),
               InferRequestedOutput("OUTPUT1")]
    outputs[0].set_shared_memory("output0_data", out_size)
    outputs[1].set_shared_memory("output1_data", out_size)

    client.infer("simple_string", inputs, outputs=outputs)

    out0 = shm.get_contents_as_numpy(shm_op0, np.object_, [1, 16]).reshape(-1)
    out1 = shm.get_contents_as_numpy(shm_op1, np.object_, [1, 16]).reshape(-1)
    for i in range(16):
        if int(out0[i]) != in0[i] + in1[i]:
            sys.exit(f"error: bad sum at {i}: {out0[i]}")
        if int(out1[i]) != in0[i] - in1[i]:
            sys.exit(f"error: bad difference at {i}: {out1[i]}")

    client.unregister_system_shared_memory()
    for h in (shm_ip, shm_op0, shm_op1):
        shm.destroy_shared_memory_region(h)

print("PASS: shm string (grpc)")
