#!/usr/bin/env python3
"""Raw-stub client: INT8 tensors through explicit int_contents against the
`simple_int8` add/sub model.

Reference counterpart: grpc_explicit_int8_content_client.py
(/root/reference/src/python/examples/).
"""

import argparse
import sys

import grpc
import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

channel = grpc.insecure_channel(args.url)
stub = GRPCInferenceServiceStub(channel)

request = pb.ModelInferRequest(model_name="simple_int8", id="explicit-int8")
in0 = np.arange(16, dtype=np.int8)          # small values: no overflow
in1 = np.full(16, 3, dtype=np.int8)
for name, arr in (("INPUT0", in0), ("INPUT1", in1)):
    t = request.inputs.add(name=name, datatype="INT8", shape=[1, 16])
    t.contents.int_contents.extend(int(x) for x in arr)
request.outputs.add(name="OUTPUT0")
request.outputs.add(name="OUTPUT1")

response = stub.ModelInfer(request)

outputs = {}
for tensor, raw in zip(response.outputs, response.raw_output_contents):
    outputs[tensor.name] = np.frombuffer(raw, np.int8)
if not np.array_equal(outputs["OUTPUT0"], in0 + in1):
    sys.exit(f"error: bad sum {outputs['OUTPUT0']}")
if not np.array_equal(outputs["OUTPUT1"], in0 - in1):
    sys.exit(f"error: bad difference {outputs['OUTPUT1']}")

print("PASS: explicit int8 content")
