#!/usr/bin/env python3
"""Raw-stub client: BYTES tensors through explicit bytes_contents against
the `simple_identity` passthrough model.

Reference counterpart: grpc_explicit_byte_content_client.py
(/root/reference/src/python/examples/).
"""

import argparse
import sys

import grpc
import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.codec import deserialize_bytes_tensor
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

channel = grpc.insecure_channel(args.url)
stub = GRPCInferenceServiceStub(channel)

values = [b"tpu", b"native", b"framework", b"bytes-content"]
request = pb.ModelInferRequest(model_name="simple_identity",
                               id="explicit-bytes")
t = request.inputs.add(name="INPUT0", datatype="BYTES",
                       shape=[1, len(values)])
t.contents.bytes_contents.extend(values)
request.outputs.add(name="OUTPUT0")

response = stub.ModelInfer(request)

raw = response.raw_output_contents[0]
got = [bytes(x) for x in np.ravel(deserialize_bytes_tensor(raw))]
if got != values:
    sys.exit(f"error: {got} != {values}")

print("PASS: explicit byte content")
