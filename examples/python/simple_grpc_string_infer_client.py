#!/usr/bin/env python3
"""BYTES (string) tensor inference over gRPC: decimal-string add/sub through
the 4-byte-length-prefixed BYTES codec.

Reference counterpart: src/python/examples/simple_grpc_string_infer_client.py.
"""

import argparse
import sys

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("-u", "--url", default="localhost:8001")
args = parser.parse_args()

in0 = np.arange(16, dtype=np.int32)
in1 = np.ones(16, dtype=np.int32)
in0_str = np.array([str(x).encode() for x in in0],
                   dtype=np.object_).reshape(1, 16)
in1_str = np.array([str(x).encode() for x in in1],
                   dtype=np.object_).reshape(1, 16)

with InferenceServerClient(args.url) as client:
    inputs = [InferInput("INPUT0", [1, 16], "BYTES"),
              InferInput("INPUT1", [1, 16], "BYTES")]
    inputs[0].set_data_from_numpy(in0_str)
    inputs[1].set_data_from_numpy(in1_str)

    result = client.infer("simple_string", inputs)

    out0 = result.as_numpy("OUTPUT0").reshape(-1)
    out1 = result.as_numpy("OUTPUT1").reshape(-1)
    for i in range(16):
        if int(out0[i]) != in0[i] + in1[i]:
            sys.exit(f"error: bad sum at {i}: {out0[i]}")
        if int(out1[i]) != in0[i] - in1[i]:
            sys.exit(f"error: bad difference at {i}: {out1[i]}")

print("PASS: string infer (grpc)")
