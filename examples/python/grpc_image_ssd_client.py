#!/usr/bin/env python3
"""SSD-MobileNet object-detection client over gRPC.

Counterpart of the fork-added reference example
src/python/examples/grpc_image_ssd_client.py:486 (raw generated stubs, COCO
labels, box drawing): sends a UINT8 NHWC 300x300x3 image to the TFLite-style
SSD model and prints detections [boxes, classes, scores, count] with COCO
label names (models/ssd_mobilenet_v2_coco_quantized/labels.txt when
present).
"""

import argparse
import sys
from pathlib import Path

import numpy as np

from client_tpu.grpc import InferenceServerClient, InferInput

parser = argparse.ArgumentParser()
parser.add_argument("image", nargs="?", help="image file (needs PIL)")
parser.add_argument("-u", "--url", default="localhost:8001")
parser.add_argument("-m", "--model", default="ssd_mobilenet_v2_coco_quantized")
parser.add_argument("-t", "--threshold", type=float, default=0.3)
args = parser.parse_args()

LABELS_FILE = (Path(__file__).resolve().parents[2] / "models" /
               "ssd_mobilenet_v2_coco_quantized" / "labels.txt")
labels = (LABELS_FILE.read_text().splitlines()
          if LABELS_FILE.exists() else [])


def load_image():
    if args.image:
        try:
            from PIL import Image
        except ImportError:
            sys.exit("PIL not available; run without an image argument to "
                     "use a synthetic input")
        img = Image.open(args.image).convert("RGB").resize((300, 300))
        return np.asarray(img, dtype=np.uint8)
    rng = np.random.default_rng(3)
    return rng.integers(0, 256, size=(300, 300, 3), dtype=np.uint8)


image = load_image()

with InferenceServerClient(args.url) as client:
    inp = InferInput("normalized_input_image_tensor", [1, 300, 300, 3],
                     "UINT8")
    inp.set_data_from_numpy(image[None])
    result = client.infer(args.model, [inp])

    # outputs are [batch, 1, N(, 4)]-shaped; flatten the singleton dims
    boxes = result.as_numpy("TFLite_Detection_PostProcess").reshape(-1, 4)
    classes = np.ravel(result.as_numpy("TFLite_Detection_PostProcess:1"))
    scores = np.ravel(result.as_numpy("TFLite_Detection_PostProcess:2"))
    count = int(np.ravel(result.as_numpy("TFLite_Detection_PostProcess:3"))[0])

    shown = 0
    for i in range(count):
        if scores[i] < args.threshold:
            continue
        cls = int(classes[i])
        name = labels[cls] if cls < len(labels) else str(cls)
        ymin, xmin, ymax, xmax = boxes[i]
        print(f"  {name}: {scores[i]:.2f} "
              f"[{ymin:.2f},{xmin:.2f},{ymax:.2f},{xmax:.2f}]")
        shown += 1
    print(f"{count} detections ({shown} above threshold)")

print("PASS: ssd detection")
