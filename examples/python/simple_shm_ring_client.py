#!/usr/bin/env python3
"""Zero-copy shm ring example: stage requests into a slot ring, ring ONE
batched doorbell for the whole span, and poll shm for completions — no
per-request HTTP round trip and no tensor bytes on the wire.

Run against a co-located server (the ring is POSIX shm, so client and
server must share /dev/shm):

    python simple_shm_ring_client.py -u localhost:8000
"""

import argparse
import sys

import numpy as np

from client_tpu.http import InferenceServerClient, RingProducer

SPAN = 8


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000",
                        help="server URL host:port")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with InferenceServerClient(args.url, verbose=args.verbose) as client:
        extensions = client.get_server_metadata()["extensions"]
        if "shm_ring" not in extensions:
            print("FAIL: server does not advertise the shm_ring extension")
            sys.exit(1)

        b = np.ones((1, 16), dtype=np.int32)
        with RingProducer(client, "example_ring", "/example_shm_ring",
                          slot_count=16, slot_bytes=4096) as producer:
            # Stage a whole span of requests into ring slots (zero-copy:
            # the server reads them straight out of /dev/shm)...
            for i in range(SPAN):
                a = np.arange(16, dtype=np.int32).reshape(1, 16) + i
                slot = producer.fill({"INPUT0": a, "INPUT1": b})
                assert slot is not None, "ring unexpectedly full"
            # ...then submit all of them with ONE control-channel call.
            result = producer.doorbell("simple")
            print(f"doorbell: {result['admitted']} slot(s) admitted in "
                  "one round trip")
            if result["admitted"] != SPAN:
                print(f"FAIL: expected {SPAN} admitted, got {result}")
                sys.exit(1)
            # Completions land in shm; poll the slot state words.
            for i in range(SPAN):
                a = np.arange(16, dtype=np.int32).reshape(1, 16) + i
                slot, outputs, error = producer.reap(timeout_s=120)
                if error is not None:
                    print(f"FAIL: slot {slot}: {error}")
                    sys.exit(1)
                if not np.array_equal(outputs["OUTPUT0"], a + b) or \
                        not np.array_equal(outputs["OUTPUT1"], a - b):
                    print(f"FAIL: slot {slot} returned wrong results")
                    sys.exit(1)
                if args.verbose:
                    print(f"slot {slot}: OUTPUT0={outputs['OUTPUT0'][0][:4]}"
                          f"... OUTPUT1={outputs['OUTPUT1'][0][:4]}...")
            status = client.get_shm_ring_status("example_ring")
            ring = status["example_ring"]
            print(f"ring status: {ring['slots_ok']} ok / "
                  f"{ring['doorbells']} doorbell(s), occupancy "
                  f"{ring['occupancy']}/{ring['slot_count']}")

    print("PASS: shm_ring")


if __name__ == "__main__":
    main()
